"""Collective/ICI traffic analyzer (analysis/comms.py) + the two
round-22 audit rules.

Four layers under test: the extractor/pricer itself (hand-built
shard_map programs per collective kind with EXACT byte/hop
expectations — the ring model's semantics are pinned), phase
attribution on the real per-phase-gated 2D campaign (each px gather
lands on its protocol phase), the lints (the known-bad legacy
unpacked-exchange fixture trips gspmd-insertion naming the phase; the
partial-axis-psum fixture trips replication-drift naming the leak; the
registered mesh programs pass both), and the single-device identity
(every px exchange lowers to ZERO collective equations on a 1-device
tile axis — solo programs provably pay no fabric tax, asserted on the
jaxpr via the extractor)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import AbstractMesh, PartitionSpec as P

from graphite_tpu.analysis import comms, rules
from graphite_tpu.analysis.audit import (
    audit_program, default_programs, spec_from_sweep,
)
from graphite_tpu.analysis.cost import COMMS_METRICS, cost_report
from graphite_tpu.parallel.mesh import TILE_AXIS_2D, _shard_map
from graphite_tpu.parallel.px import ParallelCtx

TILES = 8
DT = 4  # devices on the tile axis in the hand-built programs
TL = TILES // DT


def _mesh():
    return AbstractMesh(((TILE_AXIS_2D, DT),))


def _lower(body, in_specs, out_specs, *args):
    fn = _shard_map(body, mesh=_mesh(), in_specs=in_specs,
                    out_specs=out_specs)
    return jax.make_jaxpr(fn)(*args)


def _extract(closed, phase_names=()):
    return comms.extract_collectives(
        closed, n_tiles=TILES, phase_names=phase_names,
        axis_env=comms.mesh_axis_sizes(closed))


@pytest.fixture(scope="module")
def mesh_specs():
    """Both registered mesh programs, lowered once per module."""
    return default_programs(
        TILES, names=("sweep-b4-2d", "gated-msi-2d"))


# ---------------------------------------------------------------------------
# extraction + ICI pricing: exact per-kind expectations
# ---------------------------------------------------------------------------


class TestExtraction:
    def test_all_gather_px_exchange(self):
        """A tiled full-axis int64 all_gather of [Tl, 3]: shard = 2*3*8
        = 48 B, ICI = (n-1) x shard = 144 B over n-1 = 3 hops, payload
        = the full [T, 3] buffer = 192 B, kind px-exchange."""
        def body(x):
            return jax.lax.all_gather(x, TILE_AXIS_2D, axis=0,
                                      tiled=True)

        closed = _lower(body, (P(TILE_AXIS_2D),), P(),
                        jax.ShapeDtypeStruct((TILES, 3), jnp.int64))
        (c,) = _extract(closed)
        assert c.primitive == "all_gather"
        assert c.axis_size == DT
        assert c.shard_bytes == TL * 3 * 8 == 48
        assert c.payload_bytes == TILES * 3 * 8 == 192
        assert c.ici_bytes == (DT - 1) * 48 == 144
        assert c.hops == DT - 1 == 3
        assert c.kind == comms.KIND_PX

    def test_psum_replication_reduction(self):
        """A full-axis psum of int64[8]: ring all-reduce pays
        2(n-1)/n x 64 B = 96 B over 3 hops; full-axis psum-likes are
        the declared replication reductions."""
        def body(x):
            return jax.lax.psum(x, TILE_AXIS_2D)

        closed = _lower(body, (P(TILE_AXIS_2D),), P(),
                        jax.ShapeDtypeStruct((TILES,), jnp.int64))
        (c,) = _extract(closed)
        assert c.primitive == "psum"
        assert c.shard_bytes == TL * 8 == 16
        assert c.ici_bytes == (2 * (DT - 1) * 16) // DT == 24
        assert c.hops == DT - 1
        assert c.kind == comms.KIND_REDUCTION

    def test_ppermute_ring_distance(self):
        """A ppermute shifting by 1 on a 4-ring moves its whole payload
        exactly 1 hop; the engine never emits one, so it is a stray."""
        perm = [(i, (i + 1) % DT) for i in range(DT)]

        def body(x):
            return jax.lax.ppermute(x, TILE_AXIS_2D, perm)

        closed = _lower(body, (P(TILE_AXIS_2D),), P(TILE_AXIS_2D),
                        jax.ShapeDtypeStruct((TILES,), jnp.int64))
        (c,) = _extract(closed)
        assert c.primitive == "ppermute"
        assert c.hops == 1
        assert c.ici_bytes == c.shard_bytes == TL * 8
        assert c.kind == comms.KIND_STRAY

    def test_ppermute_long_hop(self):
        """An exchange across the ring diameter (0 <-> 2 on a 4-ring)
        is 2 hops either way round."""
        perm = [(0, 2), (2, 0)]

        def body(x):
            return jax.lax.ppermute(x, TILE_AXIS_2D, perm)

        closed = _lower(body, (P(TILE_AXIS_2D),), P(TILE_AXIS_2D),
                        jax.ShapeDtypeStruct((TILES,), jnp.int64))
        (c,) = _extract(closed)
        assert c.hops == 2
        assert c.ici_bytes == 2 * c.shard_bytes

    def test_all_to_all_pricing(self):
        """all_to_all keeps 1/n of the shard local: (n-1)/n x shard
        crosses the fabric.  Never emitted by the engine -> stray."""
        def body(x):
            return jax.lax.all_to_all(x, TILE_AXIS_2D, split_axis=1,
                                      concat_axis=0, tiled=True)

        closed = _lower(body, (P(TILE_AXIS_2D),), P(TILE_AXIS_2D),
                        jax.ShapeDtypeStruct((TILES, DT), jnp.int64))
        (c,) = _extract(closed)
        assert c.primitive == "all_to_all"
        shard = TL * DT * 8
        assert c.shard_bytes == shard
        assert c.ici_bytes == ((DT - 1) * shard) // DT
        assert c.kind == comms.KIND_STRAY

    def test_grouped_psum_is_stray(self):
        """A partial-axis (grouped) psum is never a declared
        replication reduction: group size replaces n in the pricing and
        the kind is stray."""
        def body(x):
            return jax.lax.psum(x, TILE_AXIS_2D,
                                axis_index_groups=[[0, 1], [2, 3]])

        closed = _lower(body, (P(TILE_AXIS_2D),), P(TILE_AXIS_2D),
                        jax.ShapeDtypeStruct((TILES,), jnp.int64))
        (c,) = _extract(closed)
        assert c.axis_size == 2
        assert c.kind == comms.KIND_STRAY

    def test_uint8_all_gather_is_stray(self):
        """The px whitelist pins the PACKED exchange: every field rides
        the int64 descriptor.  A narrow per-field gather is exactly the
        GSPMD-cliff shape and must classify stray."""
        def body(x):
            return jax.lax.all_gather(x, TILE_AXIS_2D, axis=0,
                                      tiled=True)

        closed = _lower(body, (P(TILE_AXIS_2D),), P(),
                        jax.ShapeDtypeStruct((TILES,), jnp.uint8))
        (c,) = _extract(closed)
        assert c.kind == comms.KIND_STRAY


# ---------------------------------------------------------------------------
# single-device identity: zero collectives on a 1-device tile axis
# ---------------------------------------------------------------------------


class TestSingleDeviceIdentity:
    def test_ctx_not_sharded_on_one_device(self):
        assert not ParallelCtx(axis=TILE_AXIS_2D, n_dev=1).sharded
        assert ParallelCtx(axis=TILE_AXIS_2D, n_dev=2).sharded
        assert not ParallelCtx().sharded

    def test_px_exchange_identity_jaxpr(self):
        """ctx.ag(ctx.lo(x)) on a 1-device tile axis must lower to ZERO
        collective equations (extractor-asserted); the same program on
        2 devices emits exactly one packed all_gather."""
        def body_for(ctx):
            def body(x):
                return ctx.ag(ctx.lo(x))

            return body

        mesh1 = AbstractMesh(((TILE_AXIS_2D, 1),))
        ctx1 = ParallelCtx(axis=TILE_AXIS_2D, n_dev=1)
        fn1 = _shard_map(body_for(ctx1), mesh=mesh1,
                         in_specs=(P(),), out_specs=P())
        closed1 = jax.make_jaxpr(fn1)(
            jax.ShapeDtypeStruct((TILES, 2), jnp.int64))
        assert comms.extract_collectives(
            closed1, n_tiles=TILES,
            axis_env=comms.mesh_axis_sizes(closed1)) == []

        mesh2 = AbstractMesh(((TILE_AXIS_2D, 2),))
        ctx2 = ParallelCtx(axis=TILE_AXIS_2D, n_dev=2)
        fn2 = _shard_map(body_for(ctx2), mesh=mesh2,
                         in_specs=(P(),), out_specs=P())
        closed2 = jax.make_jaxpr(fn2)(
            jax.ShapeDtypeStruct((TILES, 2), jnp.int64))
        cs = comms.extract_collectives(
            closed2, n_tiles=TILES,
            axis_env=comms.mesh_axis_sizes(closed2))
        assert [c.kind for c in cs] == [comms.KIND_PX]

    def test_degenerate_tile_layout_lowers_no_collectives(self):
        """A (db, 1) campaign layout shards only the batch axis; the
        size-1 tile axis must cost nothing — the WHOLE lowered program
        carries zero collective equations."""
        from graphite_tpu.config import ConfigFile, SimConfig
        from graphite_tpu.sweep import SweepRunner
        from graphite_tpu.tools._template import config_text
        from graphite_tpu.trace import synthetic

        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, shared_mem=True, clock_scheme="lax_barrier")))
        traces = [synthetic.memory_stress_trace(
            TILES, n_accesses=16, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.5, seed=s)
            for s in (1, 2)]
        runner = SweepRunner(sc, traces, layout=(2, 1))
        spec = spec_from_sweep("b2x1", runner, 4096)
        assert comms.has_mesh_region(spec.closed)
        assert comms.extract_collectives(
            spec.closed, n_tiles=TILES,
            axis_env=comms.mesh_axis_sizes(spec.closed)) == []
        assert comms.collective_metrics(spec) == {
            "collectives_per_iter": 0, "ici_bytes_per_iter": 0}


# ---------------------------------------------------------------------------
# phase attribution on the real gated 2D campaign
# ---------------------------------------------------------------------------


class TestPhaseAttribution:
    def test_gated_2d_per_phase_counts(self, mesh_specs):
        """The per-phase-gated 2D program emits exactly one packed px
        exchange per exchanging phase — two ride the requester leg (the
        pre-cond working-set gather + the in-cond exchange), one each
        for home_evict, sharer and requester_fill — all px-exchange
        kind over the 2-device tile axis."""
        spec = next(s for s in mesh_specs if s.name == "gated-msi-2d")
        rep = comms.comms_report(spec)
        counts = {r.phase: r.collectives for r in rep.phase_rows()}
        assert counts == {"requester": 2, "home_evict": 1,
                          "sharer": 1, "requester_fill": 1}
        assert all(c.kind == comms.KIND_PX for c in rep.collectives)
        assert all(c.axis_size == 2 for c in rep.collectives)
        assert rep.collectives_per_iter == 5
        assert rep.ici_bytes_per_iter == sum(
            c.ici_bytes for c in rep.collectives) > 0

    def test_vmapped_2d_attributes_base(self, mesh_specs):
        """sweep-b4-2d's vmapped layout traded its phase conds for
        masked always-run phases, so every collective lands on the
        'base' phase — and all five are whitelisted px exchanges."""
        spec = next(s for s in mesh_specs if s.name == "sweep-b4-2d")
        rep = comms.comms_report(spec)
        assert [r.phase for r in rep.phase_rows()] == [comms.BASE_PHASE]
        assert rep.collectives_per_iter == 5
        assert all(c.kind == comms.KIND_PX for c in rep.collectives)


# ---------------------------------------------------------------------------
# the lints
# ---------------------------------------------------------------------------


class TestGspmdInsertionLint:
    def test_known_bad_fixture_fires_with_phase(self):
        """The legacy unpacked-exchange fixture (one narrow collective
        per field inside a real phase cond) must trip the lint with
        error severity, naming the collectives' protocol phase."""
        spec = comms.gspmd_insertion_fixture(TILES)
        fs = rules.gspmd_insertion(spec.closed, spec.n_tiles,
                                   phase_names=spec.phase_names)
        assert len(fs) == 2
        assert all(f.severity == rules.SEV_ERROR for f in fs)
        assert all("requester" in f.message for f in fs)
        assert all(f.data["kind"] == comms.KIND_STRAY for f in fs)

    def test_fixture_fails_only_gspmd_rule(self):
        """Under the full auditor the fixture's ONLY failing rule is
        gspmd-insertion — the self-test isolates the gate."""
        spec = comms.gspmd_insertion_fixture(TILES)
        results = audit_program(spec)
        failing = [r.rule for r in results if not r.ok]
        assert failing == ["gspmd-insertion"]

    def test_registered_mesh_programs_clean(self, mesh_specs):
        for spec in mesh_specs:
            assert rules.gspmd_insertion(
                spec.closed, spec.n_tiles,
                phase_names=spec.phase_names) == []


class TestReplicationDriftLint:
    def test_partial_axis_psum_leak_fires(self):
        """A grouped psum feeding a declared-replicated output is the
        leak the rule exists for: error severity, the grouped psum
        named as the variance source."""
        spec = comms.replication_drift_fixture(TILES, leak=True)
        fs = rules.replication_drift(spec.closed)
        assert len(fs) == 1
        assert fs[0].severity == rules.SEV_ERROR
        assert any(lk["primitive"] == "psum"
                   for lk in fs[0].data["leaks"])

    def test_full_axis_psum_proves_uniform(self):
        spec = comms.replication_drift_fixture(TILES, leak=False)
        assert rules.replication_drift(spec.closed) == []

    def test_registered_mesh_programs_prove_uniform(self, mesh_specs):
        """The engine's replication contract holds on both registered
        mesh programs: every declared-replicated carry slot is provably
        uniform (and each program declares a non-trivial set of them)."""
        for spec in mesh_specs:
            assert rules.replication_drift(spec.closed) == []
            rows = comms.shard_map_uniformity(spec.closed)
            assert rows, spec.name
            assert any(r["declared_replicated"] for r in rows), spec.name


# ---------------------------------------------------------------------------
# budget metric wiring (cost.py)
# ---------------------------------------------------------------------------


class TestBudgetWiring:
    def test_mesh_program_metrics_present(self, mesh_specs):
        spec = next(s for s in mesh_specs if s.name == "gated-msi-2d")
        rep = cost_report(spec)
        m = rep.metrics()
        for k in COMMS_METRICS:
            assert k in m
        assert m["collectives_per_iter"] == 5
        assert m["ici_bytes_per_iter"] > 0

    def test_non_mesh_program_metrics_absent(self):
        """Non-mesh programs carry NO comms keys — the byte-identity
        guarantee for every pre-round-22 BUDGETS.json entry."""
        spec = default_programs(TILES, names=("gated-msi",))[0]
        assert not comms.has_mesh_region(spec.closed)
        assert comms.collective_metrics(spec) is None
        m = cost_report(spec).metrics()
        for k in COMMS_METRICS:
            assert k not in m
