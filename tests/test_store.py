"""Persistent AOT program store (graphite_tpu/store/): the on-disk
layout, the integrity/quarantine matrix, locking, GC, the CLI, and the
fleet-amortization contract through the campaign service.

The contract pins:
 - filesystem layer: atomic publication (manifest last), put/get round
   trip, checksum/truncation/version/fingerprint failures each raise a
   NAMED `StoreIntegrityError` AND quarantine the entry (rename to
   `.corrupt-*`) — corrupted artifacts are never served and never
   deleted silently; byte-budgeted LRU GC keeps the most-recently-used
   entry; concurrent writers serialize on the advisory lock and the
   losing writer's blob is discarded (the store stays sound);
 - fleet-once compilation: two fresh `CampaignService` instances over
   one shared store compile a class EXACTLY once total (probe counts
   real `Lowered.compile` calls, not bookkeeping), results bit-equal
   with the store on vs off, and every integrity failure falls back to
   a fresh compile — loudly, never a crash, never a wrong program;
 - the dwell knob: `max_dwell_s` holds an UNDER-FULL batch until its
   head job has waited the window; full batches and requeued splits
   never wait; 0 keeps the wait-for-nothing scheduler.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.serve import CampaignService, Job
from graphite_tpu.store import (
    ProgramStore, StoreIntegrityError, StoreKey,
)
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

TILES = 4
ENV = ("jax-x", "jaxlib-y", "cpu", 1)


def _key(fp="gfp1:" + "a" * 64, batch=2, max_quanta=1000, env=ENV):
    return StoreKey(fingerprint=fp, batch=batch, max_quanta=max_quanta,
                    env=env)


def _store(tmp_path, **kw):
    return ProgramStore(str(tmp_path / "store"), **kw)


# ---------------------------------------------------------------------------
# filesystem layer (fake blobs, no jax)
# ---------------------------------------------------------------------------


class TestStoreLayout:
    def test_put_get_round_trip(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        man = st.put_blob(key, b"payload-bytes",
                          manifest={"name": "cls-a", "compile_s": 1.5})
        assert man["fingerprint"] == key.fingerprint
        assert man["payload_bytes"] == len(b"payload-bytes")
        blob, man2 = st.get_blob(key)
        assert blob == b"payload-bytes"
        assert man2["name"] == "cls-a"
        assert man2["compile_s"] == 1.5
        # manifest is the publication: both files exist, valid JSON
        edir = os.path.join(st.root, "entries", key.entry_id)
        assert sorted(os.listdir(edir)) == ["last_used", "manifest.json",
                                            "program.bin"]

    def test_miss_is_none_not_error(self, tmp_path):
        assert _store(tmp_path).get_blob(_key()) is None

    def test_key_axes_are_distinct_entries(self, tmp_path):
        st = _store(tmp_path)
        base = _key()
        variants = [
            _key(fp="gfp1:" + "b" * 64),
            _key(batch=4),
            _key(max_quanta=2000),
            _key(env=("jax-z",) + ENV[1:]),
        ]
        ids = {base.entry_id} | {k.entry_id for k in variants}
        assert len(ids) == 5
        st.put_blob(base, b"x")
        for k in variants:
            assert st.get_blob(k) is None

    def test_race_existing_valid_entry_wins(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        st.put_blob(key, b"first", manifest={"name": "first"})
        man = st.put_blob(key, b"second", manifest={"name": "second"})
        assert man["name"] == "first"
        assert st.counters["races"] == 1
        assert st.get_blob(key)[0] == b"first"


class TestIntegrityMatrix:
    """Every named corruption mode: quarantine + named raise + the
    next lookup is a clean miss (so the caller recompiles)."""

    def _filled(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        st.put_blob(key, b"good-payload", manifest={"name": "cls"})
        return st, key, os.path.join(st.root, "entries", key.entry_id)

    def _assert_quarantined(self, st, key, reason):
        with pytest.raises(StoreIntegrityError) as ei:
            st.get_blob(key)
        assert ei.value.reason == reason
        root = os.path.join(st.root, "entries")
        assert any(".corrupt-" in d for d in os.listdir(root))
        assert st.counters["integrity"] == 1
        # quarantined == gone from the serving path: clean miss now
        assert st.get_blob(key) is None

    def test_checksum_corruption(self, tmp_path):
        st, key, edir = self._filled(tmp_path)
        with open(os.path.join(edir, "program.bin"), "wb") as f:
            f.write(b"good-paylobd")    # same length, flipped byte
        self._assert_quarantined(st, key, "checksum")

    def test_truncated_payload(self, tmp_path):
        st, key, edir = self._filled(tmp_path)
        with open(os.path.join(edir, "program.bin"), "wb") as f:
            f.write(b"good")
        self._assert_quarantined(st, key, "truncated")

    def test_missing_payload(self, tmp_path):
        st, key, edir = self._filled(tmp_path)
        os.remove(os.path.join(edir, "program.bin"))
        self._assert_quarantined(st, key, "truncated")

    def test_version_drift(self, tmp_path):
        st, key, edir = self._filled(tmp_path)
        mpath = os.path.join(edir, "manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        man["env"] = ["jax-older"] + man["env"][1:]
        with open(mpath, "w") as f:
            json.dump(man, f)
        self._assert_quarantined(st, key, "version")

    def test_stale_fingerprint_vs_expectation(self, tmp_path):
        """The caller's registry-resolved fingerprint outranks the
        manifest: a stale artifact recompiles, never serves."""
        st, key, edir = self._filled(tmp_path)
        with pytest.raises(StoreIntegrityError) as ei:
            st.get_blob(key, expect_fingerprint="gfp1:" + "f" * 64)
        assert ei.value.reason == "fingerprint"
        assert st.get_blob(key) is None    # quarantined

    def test_malformed_manifest(self, tmp_path):
        st, key, edir = self._filled(tmp_path)
        with open(os.path.join(edir, "manifest.json"), "w") as f:
            f.write("{not json")
        self._assert_quarantined(st, key, "manifest")

    def test_unloadable_payload_quarantines_on_deserialize(
            self, tmp_path):
        """A checksum-valid blob that is not an AOT payload fails at
        the deserialize layer with the same quarantine discipline."""
        st, key, _ = self._filled(tmp_path)
        with pytest.raises(StoreIntegrityError) as ei:
            st.load_executable(key)
        assert ei.value.reason == "deserialize"
        assert st.get_blob(key) is None

    def test_verify_is_nonquarantining(self, tmp_path):
        st, key, edir = self._filled(tmp_path)
        with open(os.path.join(edir, "program.bin"), "ab") as f:
            f.write(b"x")
        [row] = st.verify()
        assert not row["ok"] and row["reason"] == "truncated"
        # verify reported but did NOT move the entry
        assert os.path.isdir(edir)
        assert st.counters["integrity"] == 0

    def test_verify_catches_entry_not_living_at_its_key(self, tmp_path):
        # a dir restored under the wrong id (or a manifest whose key
        # fields were edited consistently with its checksum) would
        # quarantine at the first real request — verify must fail it
        # too, not bless a store that cannot serve
        st, key, edir = self._filled(tmp_path)
        wrong = os.path.join(os.path.dirname(edir), "f" * 40)
        os.rename(edir, wrong)
        [row] = st.verify()
        assert not row["ok"] and row["reason"] == "manifest"
        assert key.entry_id in row["message"]
        assert os.path.isdir(wrong)     # still non-quarantining


class TestGcAndEviction:
    def _fill(self, st, n, size=100):
        keys = []
        for i in range(n):
            k = _key(fp=f"gfp1:{i:064d}")
            st.put_blob(k, bytes(size), manifest={"name": f"c{i}"})
            keys.append(k)
        return keys

    def test_lru_gc_to_byte_budget(self, tmp_path):
        st = _store(tmp_path)
        keys = self._fill(st, 3)
        st.get_blob(keys[0])            # 0 is now most-recently-used
        sizes = {r["entry_id"]: r["bytes"] for r in st.entries()}
        budget = sizes[keys[0].entry_id] + sizes[keys[2].entry_id]
        evicted = st.gc(budget)
        assert evicted == [keys[1].entry_id]
        assert {r["entry_id"] for r in st.entries()} \
            == {keys[0].entry_id, keys[2].entry_id}
        assert st.total_bytes <= budget

    def test_mru_entry_survives_even_over_budget(self, tmp_path):
        st = _store(tmp_path)
        self._fill(st, 2)
        evicted = st.gc(1)              # budget smaller than any entry
        assert len(evicted) == 1
        assert len(st.entries()) == 1

    def test_auto_gc_on_fill(self, tmp_path):
        st = _store(tmp_path)
        st.max_bytes = 1               # every fill triggers eviction
        self._fill(st, 3)
        assert len(st.entries()) == 1
        assert st.counters["evictions"] == 2

    def test_evict_refuses_path_traversal_ids(self, tmp_path):
        # the id is a listing name, never a path: "entries/.." IS the
        # store root and rmtree would eat the whole store
        st = _store(tmp_path)
        keys = self._fill(st, 1)
        for bad in ("..", ".", "", os.path.join("..", "entries"),
                    f"subdir{os.sep}{keys[0].entry_id}"):
            assert not st.evict(bad)
        assert os.path.isdir(os.path.join(st.root, "entries"))
        assert os.path.isdir(os.path.join(st.root, "locks"))
        assert len(st.entries()) == 1
        assert st.counters["evictions"] == 0

    def test_evict_and_purge_corrupt(self, tmp_path):
        st = _store(tmp_path)
        keys = self._fill(st, 2)
        assert st.evict(keys[0].entry_id)
        assert not st.evict(keys[0].entry_id)
        # quarantine the survivor, then purge the wreckage
        st.quarantine(keys[1].entry_id, "checksum")
        assert st.stats()["corrupt"] == 1
        st.gc(include_corrupt=True)
        assert st.stats()["corrupt"] == 0


class TestStoreCli:
    """tools/store.py drives the same layer; regress rung 11 covers
    ls/verify/corruption end-to-end, so this pins only the flag
    semantics that layer cannot express."""

    def _filled(self, tmp_path, n=2):
        st = _store(tmp_path)
        for i in range(n):
            st.put_blob(_key(fp=f"gfp1:{i:064d}"), bytes(100),
                        manifest={"name": f"c{i}"})
        return st

    def test_gc_zero_budget_is_a_refusal_not_a_noop(self, tmp_path,
                                                    capsys):
        from graphite_tpu.tools.store import main as store_main

        st = self._filled(tmp_path)
        # the store layer reads 0 as unbounded, so a CLI 0 would
        # silently evict nothing while exiting 0 — it must refuse
        assert store_main(["--store", st.root, "gc",
                           "--max-bytes", "0"]) == 2
        assert "--max-bytes must be positive" in capsys.readouterr().err
        assert len(st.entries()) == 2
        assert store_main(["--store", st.root, "gc",
                           "--max-bytes", "1"]) == 0
        assert len(st.entries()) == 1   # MRU survivor

    def test_nondirectory_store_is_a_clean_exit_2(self, tmp_path,
                                                  capsys):
        from graphite_tpu.tools.store import main as store_main

        f = tmp_path / "not-a-dir"
        f.write_text("x")
        assert store_main(["--store", str(f), "ls"]) == 2
        assert "not a directory" in capsys.readouterr().err


class TestConcurrentWriters:
    def test_flock_serializes_writers(self, tmp_path):
        """A writer holding the entry lock blocks a second writer; the
        store ends sound with exactly one published payload."""
        st = _store(tmp_path)
        key = _key()
        order = []
        release = threading.Event()
        entered = threading.Event()

        def holder():
            with st._lock(key.entry_id):
                entered.set()
                order.append("hold")
                release.wait(10)
                order.append("release")

        def writer():
            entered.wait(10)
            st.put_blob(key, b"from-writer", manifest={"name": "w"})
            order.append("write")

        th, tw = threading.Thread(target=holder), \
            threading.Thread(target=writer)
        th.start()
        tw.start()
        entered.wait(10)
        time.sleep(0.1)        # give the writer time to block
        assert "write" not in order
        release.set()
        th.join(10)
        tw.join(10)
        assert order == ["hold", "release", "write"]
        assert st.get_blob(key)[0] == b"from-writer"

    def test_parallel_put_same_key_single_entry(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        errs = []

        def put(i):
            try:
                st.put_blob(key, f"blob-{i}".encode(),
                            manifest={"name": f"t{i}"})
            except Exception as e:     # noqa: BLE001 - test collects
                errs.append(e)

        threads = [threading.Thread(target=put, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert not errs
        assert len(st.entries()) == 1
        # whichever writer won, the entry is internally consistent
        blob, man = st.get_blob(key)
        assert blob.decode() == f"blob-{man['name'][1:]}"
        assert st.counters["fills"] + st.counters["races"] == 4
        assert st.counters["fills"] >= 1


# ---------------------------------------------------------------------------
# record serialization hardening (analysis/registry.py)
# ---------------------------------------------------------------------------


class TestRecordSerialization:
    def test_round_trip_through_manifest_json(self):
        from graphite_tpu.analysis.registry import ProgramRecord

        rec = ProgramRecord(name="serve-x", fingerprint="gfp1:ab",
                            tiles=8, knobs=("dram_latency_ns",))
        man = json.loads(json.dumps({"name": rec.name, **rec.to_json()}))
        back = ProgramRecord.from_json(man["name"], man)
        assert back == rec

    def test_malformed_record_is_a_clean_valueerror(self):
        from graphite_tpu.analysis.registry import ProgramRecord

        with pytest.raises(ValueError, match="malformed ProgramRecord"):
            ProgramRecord.from_json("x", {"tiles": 4})     # no fingerprint
        with pytest.raises(ValueError, match="malformed ProgramRecord"):
            ProgramRecord.from_json("x", {"fingerprint": "gfp1:ab",
                                          "tiles": "not-an-int"})


# ---------------------------------------------------------------------------
# fleet amortization through the service (real compiles)
# ---------------------------------------------------------------------------


def _config(tiles=TILES):
    return SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax")))


def _trace(seed, n=10, tiles=TILES):
    return synthetic.memory_stress_trace(
        tiles, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _jobs():
    return [Job(f"j{s}", _config(), _trace(s), seed=s) for s in (1, 2, 3)]


class _CompileCounter:
    """Counts REAL XLA compiles (jax.stages.Lowered.compile calls) —
    the probe that pins 'fleet-once', immune to counter bookkeeping."""

    def __init__(self, monkeypatch):
        import jax

        self.count = 0
        orig = jax.stages.Lowered.compile

        def counting(lowered, *a, **kw):
            self.count += 1
            return orig(lowered, *a, **kw)

        monkeypatch.setattr(jax.stages.Lowered, "compile", counting)


@pytest.fixture(scope="module")
def shared_store_fleet(tmp_path_factory):
    """Two fresh services over ONE store dir, plus a store-off oracle:
    the expensive compile work shared by the fleet pins below."""
    sdir = str(tmp_path_factory.mktemp("fleet") / "store")
    oracle = CampaignService(batch_size=2, max_quanta=200_000)
    for j in _jobs():
        oracle.submit(j)
    oracle_res = {r.job_id: r for r in oracle.drain()}

    svc_a = CampaignService(batch_size=2, max_quanta=200_000, store=sdir)
    for j in _jobs():
        svc_a.submit(j)
    a_res = {r.job_id: r for r in svc_a.drain()}

    svc_b = CampaignService(batch_size=2, max_quanta=200_000, store=sdir)
    warm = svc_b.warm_start()
    for j in _jobs():
        svc_b.submit(j)
    b_res = {r.job_id: r for r in svc_b.drain()}
    return sdir, oracle_res, svc_a, a_res, svc_b, b_res, warm


class TestFleetAmortization:
    def test_store_on_bit_identical_to_store_off(
            self, shared_store_fleet):
        _, oracle_res, _, a_res, _, b_res, _ = shared_store_fleet
        for jid, ref in oracle_res.items():
            for got in (a_res[jid], b_res[jid]):
                assert got.ok
                np.testing.assert_array_equal(
                    got.results.clock_ps, ref.results.clock_ps,
                    err_msg=jid)
                for k in ref.results.mem_counters:
                    np.testing.assert_array_equal(
                        got.results.mem_counters[k],
                        ref.results.mem_counters[k], err_msg=f"{jid}:{k}")

    def test_fleet_compiles_class_exactly_once_total(
            self, shared_store_fleet):
        _, _, svc_a, _, svc_b, _, warm = shared_store_fleet
        ca, cb = svc_a.counters, svc_b.counters
        # process A: the one compile + the fill
        assert ca["compile_count"] == 1
        assert ca["store_misses"] == 1 and ca["store_fills"] == 1
        assert ca["store_hits"] == 0
        # process B: warm-started, ZERO compiles, all store hits
        assert warm == 1
        assert cb["compile_count"] == 0 and cb["store_misses"] == 0
        assert cb["store_hits"] == 1
        assert cb["store_integrity"] == 0
        # B's cache entry knows it came from disk AND what the
        # original miss paid
        [entry] = svc_b.cache._entries.values()
        assert entry.source == "store"
        assert entry.compile_s > 0 and entry.deserialize_s > 0

    def test_second_fleet_member_pays_zero_real_compiles(
            self, shared_store_fleet, monkeypatch):
        """The probe: a THIRD service over the same store serves the
        class with zero `Lowered.compile` calls (counted at the jax
        layer, not our counters)."""
        sdir, oracle_res, *_ = shared_store_fleet
        probe = _CompileCounter(monkeypatch)
        svc = CampaignService(batch_size=2, max_quanta=200_000,
                              store=sdir)
        for j in _jobs():
            svc.submit(j)
        res = {r.job_id: r for r in svc.drain()}
        assert probe.count == 0
        assert svc.counters["store_hits"] == 1
        np.testing.assert_array_equal(
            res["j1"].results.clock_ps,
            oracle_res["j1"].results.clock_ps)

    def test_corrupted_entry_recompiles_loudly_never_serves(
            self, shared_store_fleet, monkeypatch):
        sdir, oracle_res, *_ = shared_store_fleet
        st = ProgramStore(sdir)
        [row] = st.entries()
        p = os.path.join(sdir, "entries", row["entry_id"], "program.bin")
        with open(p, "rb") as f:
            blob = f.read()
        with open(p, "wb") as f:
            f.write(blob[:50] + bytes([blob[50] ^ 0xFF]) + blob[51:])
        try:
            probe = _CompileCounter(monkeypatch)
            svc = CampaignService(batch_size=2, max_quanta=200_000,
                                  store=sdir)
            for j in _jobs():
                svc.submit(j)
            res = {r.job_id: r for r in svc.drain()}
            c = svc.counters
            assert c["store_integrity"] == 1       # quarantined loudly
            assert c["store_hits"] == 0
            assert probe.count == 1                # fell back to compile
            assert c["compile_count"] == 1
            # and the recompiled program is still the right one
            np.testing.assert_array_equal(
                res["j2"].results.clock_ps,
                oracle_res["j2"].results.clock_ps)
            # the wreckage is preserved for forensics
            assert ProgramStore(sdir).stats()["corrupt"] == 1
        finally:
            # the fallback compile re-filled the store; leave it sound
            # for any later test using the fixture
            ProgramStore(sdir).gc(include_corrupt=True)

    def test_store_survives_service_restart_after_quarantine(
            self, shared_store_fleet):
        """After the corruption test's recompile-and-refill, a fresh
        service still warm-starts — the fleet self-heals."""
        sdir, *_ = shared_store_fleet
        svc = CampaignService(batch_size=2, max_quanta=200_000,
                              store=sdir)
        assert svc.warm_start() == 1


# ---------------------------------------------------------------------------
# the dwell knob (stubbed execution, fake clock — no compiles)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _stub_ok(svc):
    from graphite_tpu.serve import JobResult, STATUS_OK

    def execute(cls, pendings, batch_id):
        svc._last_residency = 0
        return [JobResult(job_id=p.job.job_id, status=STATUS_OK,
                          batch_id=batch_id, attempts=p.attempts + 1)
                for p in pendings]
    return execute


class TestDwellKnob:
    def test_default_zero_runs_immediately(self, monkeypatch):
        clk = _Clock()
        svc = CampaignService(batch_size=4, clock=clk)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("a", _config(), _trace(1)))
        assert len(svc.step()) == 1    # under-full batch, no waiting

    def test_underfull_batch_waits_out_the_window(self, monkeypatch):
        clk = _Clock()
        svc = CampaignService(batch_size=4, clock=clk, max_dwell_s=2.0)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("a", _config(), _trace(1)))
        assert svc.step() == []                 # held: dwell 0 < 2
        assert svc._dwell_wait_s == pytest.approx(2.0)
        clk.advance(1.5)
        assert svc.step() == []                 # still inside the window
        assert svc._dwell_wait_s == pytest.approx(0.5)
        clk.advance(0.5)
        out = svc.step()                        # window over: run it
        assert [r.job_id for r in out] == ["a"]
        # the dwell histogram recorded the wait the knob bought
        assert svc.metrics["queue_dwell_seconds"].max \
            == pytest.approx(2.0)

    def test_full_batch_never_waits(self, monkeypatch):
        clk = _Clock()
        svc = CampaignService(batch_size=2, clock=clk, max_dwell_s=60.0)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("a", _config(), _trace(1)))
        svc.submit(Job("b", _config(), _trace(2)))
        assert len(svc.step()) == 2     # capacity reached: no hold

    def test_filling_during_the_window_releases_early(self, monkeypatch):
        clk = _Clock()
        svc = CampaignService(batch_size=2, clock=clk, max_dwell_s=10.0)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("a", _config(), _trace(1)))
        assert svc.step() == []
        clk.advance(1.0)
        svc.submit(Job("b", _config(), _trace(2)))
        assert len(svc.step()) == 2     # filled: runs 9 s early

    def test_force_and_frozen_clock_drain_terminate(self, monkeypatch):
        clk = _Clock()
        svc = CampaignService(batch_size=4, clock=clk, max_dwell_s=5.0)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("a", _config(), _trace(1)))
        assert len(svc.step(force=True)) == 1
        # a frozen injected clock cannot age the head job: drain must
        # force rather than spin
        svc.submit(Job("b", _config(), _trace(2)))
        out = list(svc.drain())
        assert [r.job_id for r in out] == ["b"]

    def test_full_class_runs_while_held_head_ages(self, monkeypatch):
        """The hold applies to the globally-oldest UNDER-FULL head
        only: a different class whose queue can already fill a batch
        runs immediately (a full batch gains nothing by waiting), and
        the held head keeps aging meanwhile."""
        clk = _Clock()
        svc = CampaignService(batch_size=2, clock=clk, max_dwell_s=60.0)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("a", _config(), _trace(1)))            # oldest
        svc.submit(Job("b0", _config(8), _trace(1, tiles=8)))
        svc.submit(Job("b1", _config(8), _trace(2, tiles=8)))
        out = svc.step()          # B is FULL: runs despite A's hold
        assert [r.job_id for r in out] == ["b0", "b1"]
        assert svc.step() == []   # A alone again: still held
        clk.advance(60.0)
        assert [r.job_id for r in svc.step()] == ["a"]

    def test_requeued_split_never_waits(self, monkeypatch):
        from graphite_tpu.engine.simulator import DeadlockError

        clk = _Clock()
        svc = CampaignService(batch_size=2, max_attempts=4, clock=clk,
                              max_dwell_s=60.0)
        calls = {"n": 0}

        def flaky(cls, pendings, batch_id):
            calls["n"] += 1
            if len(pendings) > 1:
                raise DeadlockError("poisoned pair")
            return _stub_ok(svc)(cls, pendings, batch_id)

        monkeypatch.setattr(svc, "_execute", flaky)
        svc.submit(Job("a", _config(), _trace(1)))
        svc.submit(Job("b", _config(), _trace(2)))
        assert svc.step() == []          # pair fails, splits
        # the split halves are PRE-FORMED: they run with no dwell hold
        done = [r.job_id for r in svc.step() + svc.step()]
        assert done == ["a", "b"]
        assert calls["n"] == 3


# ---------------------------------------------------------------------------
# reader/writer/GC arbitration under the entry lock
# ---------------------------------------------------------------------------


class TestReaderArbitration:
    """A reader that saw a torn view arbitrates under the entry lock
    before it may quarantine: a concurrently REPAIRED entry serves, a
    concurrently EVICTED entry reads as a clean miss — never a
    quarantined healthy entry, never a phantom integrity alarm for
    routine GC."""

    def _torn(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        st.put_blob(key, b"good-payload", manifest={"name": "cls"})
        edir = os.path.join(st.root, "entries", key.entry_id)
        with open(os.path.join(edir, "program.bin"), "wb") as f:
            f.write(b"good-paylobd")    # checksum fails lock-free
        return st, key, edir

    def test_repaired_entry_serves_instead_of_quarantining(
            self, tmp_path, monkeypatch):
        import contextlib

        st, key, edir = self._torn(tmp_path)
        orig = ProgramStore._lock

        @contextlib.contextmanager
        def lock_after_writer_repaired(store, name):
            with orig(store, name):
                # the racing writer held the lock FIRST and repaired
                with open(os.path.join(edir, "program.bin"), "wb") as f:
                    f.write(b"good-payload")
                yield

        monkeypatch.setattr(ProgramStore, "_lock",
                            lock_after_writer_repaired)
        blob, man = st.get_blob(key)
        assert blob == b"good-payload"
        assert man["name"] == "cls"
        assert st.counters["integrity"] == 0
        assert not any(".corrupt-" in d for d in
                       os.listdir(os.path.join(st.root, "entries")))

    def test_entry_evicted_under_reader_is_a_miss(
            self, tmp_path, monkeypatch):
        import contextlib
        import shutil

        st, key, edir = self._torn(tmp_path)
        orig = ProgramStore._lock

        @contextlib.contextmanager
        def lock_after_gc_evicted(store, name):
            with orig(store, name):
                shutil.rmtree(edir, ignore_errors=True)
                yield

        monkeypatch.setattr(ProgramStore, "_lock", lock_after_gc_evicted)
        assert st.get_blob(key) is None     # a miss, not corruption
        assert st.counters["integrity"] == 0


class TestWarmStartLimit:
    def test_limit_stages_mru_first_and_dedups(self, tmp_path,
                                               monkeypatch):
        from graphite_tpu.store import aot

        env = aot.runtime_env()
        st = _store(tmp_path)
        clk = [100.0]
        st._clock = lambda: clk[0]
        fp1, fp2 = "gfp1:" + "1" * 17, "gfp1:" + "2" * 17
        st.put_blob(_key(fp=fp1, batch=2, max_quanta=777, env=env),
                    b"one", manifest={"name": "one"})
        clk[0] = 200.0
        st.put_blob(_key(fp=fp2, batch=2, max_quanta=777, env=env),
                    b"two", manifest={"name": "two"})
        monkeypatch.setattr(aot, "deserialize_compiled",
                            lambda blob: ("exe", bytes(blob)))
        svc = CampaignService(batch_size=2, max_quanta=777, store=st)
        assert svc.warm_start(limit=1) == 1
        assert list(svc._warm) == [(fp2, 2)]    # MRU staged first
        assert svc.warm_start() == 1            # stages only the rest
        assert set(svc._warm) == {(fp1, 2), (fp2, 2)}

    def test_unreachable_store_is_a_cold_start_not_a_crash(
            self, tmp_path):
        import shutil

        st = _store(tmp_path)
        svc = CampaignService(batch_size=2, max_quanta=777, store=st)
        shutil.rmtree(st.root)
        assert svc.warm_start() == 0


class TestManifestTypeCorruption:
    def test_wrong_typed_field_is_integrity_not_crash(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        st.put_blob(key, b"good-payload", manifest={"name": "cls"})
        mpath = os.path.join(st.root, "entries", key.entry_id,
                             "manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        man["payload_bytes"] = "12a"    # JSON-valid, wrong type
        with open(mpath, "w") as f:
            json.dump(man, f)
        with pytest.raises(StoreIntegrityError) as ei:
            st.get_blob(key)
        assert ei.value.reason == "manifest"
        assert st.get_blob(key) is None    # quarantined

    def test_verify_reports_wrong_type_without_raising(self, tmp_path):
        st = _store(tmp_path)
        key = _key()
        st.put_blob(key, b"good-payload")
        mpath = os.path.join(st.root, "entries", key.entry_id,
                             "manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        man["payload_bytes"] = [12]     # int([12]) raises TypeError
        with open(mpath, "w") as f:
            json.dump(man, f)
        [row] = st.verify()
        assert not row["ok"] and row["reason"] == "manifest"


class TestLockHousekeeping:
    def test_gc_unlinks_orphan_locks_keeps_live_and_corrupt(
            self, tmp_path):
        st = _store(tmp_path)
        keys = [_key(fp=f"gfp1:{i:017d}") for i in range(3)]
        for k in keys:
            st.put_blob(k, b"x" * 8)
        st.evict(keys[0].entry_id)
        st.quarantine(keys[2].entry_id, "checksum")
        st.gc()
        locks = os.listdir(os.path.join(st.root, "locks"))
        assert f"{keys[0].entry_id}.lock" not in locks   # orphan: gone
        assert f"{keys[1].entry_id}.lock" in locks       # live entry
        assert f"{keys[2].entry_id}.lock" in locks       # quarantine
        # the surviving entry still locks and serves
        assert st.get_blob(keys[1])[0] == b"x" * 8
        st.put_blob(keys[0], b"refill")                  # lock recreated
        assert st.get_blob(keys[0])[0] == b"refill"
