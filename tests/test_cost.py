"""Static cost & residency model + budget gates (analysis/cost.py).

Three layers under test: the cost walk itself (hand-built programs with
known byte/eqn counts — the model's semantics are pinned exactly), the
budget gate (a clean program stays within its own ceilings; the
known-regression inflated-carry fixture trips them with the offending
equation named; BUDGETS.json round-trips through the CLI's
--budget-update), and the residency layer (per-consumer breakdown, the
SweepRunner pre-compile fail-fast, and the unified
ResidencyBudgetError the telemetry refusals now raise).  The CPU
oracle test cross-checks the static estimate against the backend's own
`compiled.memory_analysis()` within the documented tolerance.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from graphite_tpu.analysis import cost
from graphite_tpu.analysis.audit import default_programs
from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.sweep import SweepRunner
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

TILES = 8

GEOMETRY = """
[l1_icache/T1]
cache_size = 4
associativity = 2
[l1_dcache/T1]
cache_size = 8
associativity = 4
[l2_cache/T1]
cache_size = 32
associativity = 8
[dram_directory]
total_entries = 64
associativity = 4
"""


def _config(**over):
    return SimConfig(ConfigFile.from_string(config_text(
        TILES, shared_mem=True, clock_scheme="lax_barrier") + GEOMETRY))


def _trace(seed=7):
    return synthetic.memory_stress_trace(
        TILES, n_accesses=16, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


@pytest.fixture(scope="module")
def gated_spec():
    """The gated-MSI audited program, lowered once per module."""
    return default_programs(TILES, names=("gated-msi",))[0]


@pytest.fixture(scope="module")
def gated_report(gated_spec):
    return cost.cost_report(gated_spec)


# ---------------------------------------------------------------------------
# the cost walk: exact semantics on hand-built programs
# ---------------------------------------------------------------------------


class TestCostWalk:
    def test_peak_live_scan_exact(self):
        """Straight-line liveness: x [8 KB] -> y = x+1 -> z = y+x.
        At z both x and y are live plus z's output: 3 x 8 KB."""
        def f(x):
            y = x + 1.0
            return y + x

        closed = jax.make_jaxpr(f)(jnp.ones(1024))
        assert cost.peak_live_bytes(closed) == 3 * 8192

    def test_peak_counts_loop_carry_double_buffer(self):
        """A while carrying an 8 KB buffer: operand + loop output +
        the body's own transient — the double-buffer the round-6
        cond-payload contract prices."""
        def f(x):
            return jax.lax.while_loop(
                lambda c: c.sum() < 10, lambda c: c + 1.0, x)

        closed = jax.make_jaxpr(f)(jnp.ones(1024))
        assert cost.peak_live_bytes(closed) == 3 * 8192

    def test_dynamic_cost_scan_multiplier(self):
        """scan length multiplies its body's eqns and bytes."""
        def f(x):
            def step(c, _):
                return c + 1.0, ()
            out, _ = jax.lax.scan(step, x, None, length=10)
            return out

        closed = jax.make_jaxpr(f)(jnp.ones(1024))
        dc = cost.dynamic_cost(closed)
        # one add per scan step: 10 eqns, 10 x (in 8192 + out 8192;
        # the +1.0 literal carries no bytes)
        assert dc.eqns == 10
        assert dc.bytes_moved == 10 * (8192 + 8192)

    def test_dynamic_cost_cond_takes_heavy_branch(self):
        """cond costs its heaviest arm (the dense-iteration view), not
        both arms."""
        def f(p, x):
            return jax.lax.cond(p, lambda v: v * 2.0 + 1.0,
                                lambda v: v, x)

        closed = jax.make_jaxpr(f)(True, jnp.ones(1024))
        dc = cost.dynamic_cost(closed)
        # heavy branch: mul + add = 2 eqns (identity arm: 0), plus the
        # cond output copy counted as traffic
        assert dc.eqns == 2

    def test_free_primitives_excluded_from_kernel_proxy(self):
        def f(x):
            return jnp.reshape(x, (32, 32)).astype(jnp.float32)

        closed = jax.make_jaxpr(f)(jnp.ones(1024))
        assert cost.dynamic_cost(closed).eqns == 0

    def test_main_loop_body_finds_quantum_loop(self, gated_spec):
        body = cost.main_loop_body(gated_spec.closed)
        assert body is not None
        # the quantum loop holds the engine: most of the program's eqns
        from graphite_tpu.analysis.walk import iter_eqns

        assert sum(1 for _ in iter_eqns(body)) > 1000


# ---------------------------------------------------------------------------
# the report: real-program structure
# ---------------------------------------------------------------------------


class TestCostReport:
    def test_report_metrics_present_and_positive(self, gated_report):
        m = gated_report.metrics()
        assert set(m) == set(cost.BUDGET_METRICS)
        assert all(v > 0 for v in m.values()), m

    def test_phase_attribution_covers_all_phases(self, gated_report):
        """The per-iteration kernel proxy attributes one entry per
        protocol phase, named from the engine's own phase list."""
        from graphite_tpu.memory.engine import PHASE_NAMES

        assert {p.name for p in gated_report.phase_costs} \
            == set(PHASE_NAMES)
        assert all(p.eqns > 0 for p in gated_report.phase_costs)
        assert gated_report.base_kernels_per_iter > 0

    def test_ungated_program_has_no_phase_rows(self):
        spec = default_programs(TILES, names=("ungated-msi",))[0]
        rep = cost.cost_report(spec)
        assert rep.phase_costs == []
        assert rep.base_kernels_per_iter == rep.kernels_per_iter

    def test_top_eqns_sorted_and_sited(self, gated_report):
        tops = gated_report.top_eqns
        assert tops == sorted(tops, key=lambda r: r["out_bytes"],
                              reverse=True)
        assert all("site" in r and "primitive" in r for r in tops)

    def test_report_json_roundtrips(self, gated_report):
        row = json.loads(json.dumps(gated_report.to_json()))
        assert row["cost"] is True and row["program"] == "gated-msi"
        assert row["phases"][0]["eqns"] > 0


# ---------------------------------------------------------------------------
# the budget gate
# ---------------------------------------------------------------------------


class TestBudgetGate:
    def test_clean_program_within_own_ceilings(self, gated_report,
                                               tmp_path):
        p = str(tmp_path / "b.json")
        cost.save_budgets([gated_report], p)
        assert cost.check_budget(gated_report,
                                 cost.load_budgets(p)) == []

    def test_missing_entry_is_an_error(self, gated_report):
        findings = cost.check_budget(gated_report, {})
        assert len(findings) == 1
        assert "no budget entry" in findings[0].message

    def test_checked_in_budgets_cover_all_default_programs(self):
        from graphite_tpu.analysis.audit import DEFAULT_PROGRAM_NAMES

        budgets = cost.load_budgets()
        assert set(DEFAULT_PROGRAM_NAMES) <= set(budgets)
        for name in DEFAULT_PROGRAM_NAMES:
            entry = budgets[name]
            # every program budgets the core metrics; mesh-lowered
            # programs additionally carry the round-22 comms metrics
            assert set(cost.BUDGET_METRICS) <= set(entry["ceiling"])
            assert set(entry["ceiling"]) <= set(
                cost.BUDGET_METRICS + cost.COMMS_METRICS)
            for m in entry["ceiling"]:
                assert entry["ceiling"][m] > entry["measured"][m]

    def test_regression_fixture_trips_gate_naming_eqn(self, gated_report,
                                                      tmp_path):
        """The known-regression fixture: the gated-MSI program with a
        96 MB buffer riding an extra while carry must blow the peak
        budget, and the finding must name the offending equation."""
        p = str(tmp_path / "b.json")
        cost.save_budgets([gated_report], p)
        fix = cost.budget_regression_fixture(TILES)
        frep = cost.cost_report(fix)
        findings = cost.check_budget(frep, cost.load_budgets(p))
        metrics_hit = {f.data["metric"] for f in findings}
        assert "peak_bytes" in metrics_hit
        peak = next(f for f in findings
                    if f.data["metric"] == "peak_bytes")
        suspect = peak.data["suspect"]
        # the inflated carried buffer is the named suspect
        assert suspect["out_bytes"] >= 90 << 20
        assert "while" in suspect["site"]
        assert suspect["site"] in peak.message \
            or suspect["primitive"] in peak.message

    def test_budget_update_cli_roundtrip(self, tmp_path):
        """--budget-update writes a file --budget then passes against;
        tightening a ceiling below the measurement makes the SAME run
        exit nonzero (the gate is live, not decorative)."""
        from graphite_tpu.tools.audit import main

        p = str(tmp_path / "budgets.json")
        assert main(["--programs", "gated-msi", "--budget-update",
                     "--budgets-file", p]) == 0
        assert main(["--programs", "gated-msi", "--budget",
                     "--budgets-file", p]) == 0
        data = json.load(open(p))
        data["gated-msi"]["ceiling"]["kernels_per_iter"] = 1
        json.dump(data, open(p, "w"))
        assert main(["--programs", "gated-msi", "--budget",
                     "--budgets-file", p]) == 1


# ---------------------------------------------------------------------------
# the CPU oracle: static estimate vs compiled.memory_analysis()
# ---------------------------------------------------------------------------


class TestMemoryAnalysisOracle:
    def test_gated_msi_static_vs_backend(self, gated_report):
        """Acceptance gate: the static residency estimate for the
        gated-MSI program agrees with the backend's own accounting
        within the documented tolerance (cost.ARG_OUT_TOL for
        arguments/outputs; peak within [1, PEAK_OVER_FACTOR] x the
        backend total — the live-range scan ignores aliasing, so it
        over-estimates but must never under-estimate)."""
        sim = Simulator(_config(), _trace(), phase_gate=True,
                        mem_gate_bytes=0)
        fn, args = sim._auditable_fn(4096)
        rep = gated_report
        cmp = cost.backend_memory_comparison(fn, args, rep)
        assert cmp is not None and cmp["backend"] == "cpu"
        arg_err = abs(rep.arg_bytes - cmp["argument_bytes"]) \
            / cmp["argument_bytes"]
        out_err = abs(rep.out_bytes - cmp["output_bytes"]) \
            / cmp["output_bytes"]
        assert arg_err <= cost.ARG_OUT_TOL, (rep.arg_bytes, cmp)
        assert out_err <= cost.ARG_OUT_TOL, (rep.out_bytes, cmp)
        backend_total = (cmp["argument_bytes"] + cmp["output_bytes"]
                         + cmp["temp_bytes"])
        ratio = rep.peak_bytes / backend_total
        assert 1.0 <= ratio <= cost.PEAK_OVER_FACTOR, (ratio, cmp)
        # the comparison is recorded in the report, as documented
        assert rep.memory_cmp is cmp


# ---------------------------------------------------------------------------
# residency: breakdown, fail-fast, unified refusals
# ---------------------------------------------------------------------------


class TestResidency:
    def test_breakdown_itemizes_consumers(self):
        from graphite_tpu.obs import TelemetrySpec

        tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=32)
        sim = Simulator(_config(), _trace(), telemetry=tel)
        d = sim.residency_breakdown()
        assert d["state"] > 0 and d["trace"] > 0
        assert d["telemetry"] == sim.telemetry_spec.ring_bytes()
        assert d["total"] == d["state"] + d["trace"] + d["telemetry"]

    def test_ring_bytes_accounting(self):
        from graphite_tpu.obs import TelemetrySpec

        sim = Simulator(_config(), _trace())
        spec = TelemetrySpec(sample_interval_ps=1_000_000,
                             n_samples=32).resolve(sim.params)
        n = spec.n_series
        assert spec.ring_bytes() == 32 * n * 8 + n * 8 + 5 * 8

    def test_sweep_fail_fast_raises_named_error(self):
        """The pre-compile fail-fast: a campaign whose estimated
        residency exceeds the configured HBM budget refuses with the
        per-consumer breakdown BEFORE any tracing."""
        traces = [_trace(s) for s in (1, 2, 3, 4)]
        with pytest.raises(cost.ResidencyBudgetError) as ei:
            SweepRunner(_config(), traces, shard_batch=False,
                        hbm_budget_bytes=1024)
        msg = str(ei.value)
        assert "state" in msg and "trace" in msg and "B=4" in msg

    def test_sweep_budget_config_key_and_pass(self):
        """`[general] hbm_budget_bytes` arms the same check; a budget
        above the estimate builds normally and exposes the breakdown."""
        traces = [_trace(s) for s in (1, 2)]
        sc = SimConfig(ConfigFile.from_string(
            config_text(TILES, shared_mem=True,
                        clock_scheme="lax_barrier") + GEOMETRY
            + "[general]\nhbm_budget_bytes = 1024\n"))
        with pytest.raises(cost.ResidencyBudgetError):
            SweepRunner(sc, traces, shard_batch=False)
        runner = SweepRunner(_config(), traces, shard_batch=False,
                             hbm_budget_bytes=1 << 40)
        d = runner.residency_breakdown()
        assert d["total"] <= 1 << 40
        assert d["state"] > 0 and d["trace"] > 0

    def test_attach_telemetry_refusal_is_residency_error(self):
        """The stream/mesh telemetry rejections raise the SAME unified
        exception type, message carrying the breakdown (and still a
        ValueError: legacy callers keep working)."""
        from graphite_tpu.obs import TelemetrySpec

        sim = Simulator(_config(), _trace(), stream=True)
        with pytest.raises(cost.ResidencyBudgetError,
                           match="single-device resident") as ei:
            sim.attach_telemetry(
                TelemetrySpec(sample_interval_ps=1_000_000,
                              n_samples=32))
        msg = str(ei.value)
        assert "telemetry" in msg and "=" in msg
        assert isinstance(ei.value, ValueError)

    def test_telemetry_breakdown_scales_with_batch(self):
        """Campaign residency itemizes B telemetry rings, and the state
        item does NOT double-count the ring riding the state carry."""
        from graphite_tpu.obs import TelemetrySpec

        tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=32)
        traces = [_trace(s) for s in (1, 2, 3, 4)]
        runner = SweepRunner(_config(), traces, shard_batch=False,
                             telemetry=tel)
        d = runner.residency_breakdown()
        assert d["telemetry"] == 4 * runner.sim.telemetry_spec.ring_bytes()
        bare = cost.tree_bytes(runner.sim.state.replace(telemetry=None))
        assert d["state"] == 4 * bare


def test_budget_regression_fixture_cli_exits_nonzero(tmp_path):
    """CLI-level acceptance: `--budget --regression-fixture` must exit
    nonzero against the real checked-in BUDGETS.json."""
    from graphite_tpu.tools.audit import main

    assert main(["--budget", "--regression-fixture"]) == 1
