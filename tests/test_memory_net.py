"""The MEMORY network under emesh_hop_by_hop: coherence traffic sees
per-port contention.

Reference: every ShmemMsg routes through the configured memory network
model (`carbon_sim.cfg:281-282` memory_model_1; per-hop queues
`network_model_emesh_hop_by_hop.cc:146-265`); `tests/benchmarks/
synthetic_memory` is the reference's stress generator for exactly this.

Contract (BASELINE.md carve-outs):
 - serialized coherence traffic is BIT-EXACT vs the golden oracle's
   independent serial per-hop net (unicast flows fully independent;
   fan-out multicasts share the engine's documented inject+rank
   approximation);
 - hop_by_hop must CHANGE measured completion vs hop_counter (the
   round-2 gap was that `memory = emesh_hop_by_hop` silently degraded
   to zero-load);
 - memory = atac routes coherence messages over the optical NoC
   (clusters/hubs/waveguide, hub contention) — serialized-bit-exact vs
   the serial `_AtacNet` oracle, including ackwise broadcast sweeps.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.golden import run_golden
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import TraceBatch, TraceBuilder

MSI = "pr_l1_pr_l2_dram_directory_msi"
MOSI = "pr_l1_pr_l2_dram_directory_mosi"


def make_config(n_tiles, proto=MSI, net="emesh_hop_by_hop", extra=""):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = {net}
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[network/emesh_hop_by_hop]
flit_width = 64
[network/emesh_hop_by_hop/router]
delay = 1
[network/emesh_hop_by_hop/link]
delay = 1
[caching_protocol]
type = {proto}
[core/static_instruction_costs]
mov = 1
ialu = 1
{extra}
"""
    return SimConfig(ConfigFile.from_string(text))


def assert_exact(sc, batch):
    res = Simulator(sc, batch).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps,
                                  err_msg="clock")
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)
    return res, gold


def mutex_rmw(n, rounds, base=0x900000, lines=2):
    """Mutex-serialized shared-line read-modify-writes: at any moment one
    tile touches the shared data, so engine iteration order and oracle
    clock order coincide — the bit-exactness regime."""
    bs = [TraceBuilder() for _ in range(n)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, n)
    for b in bs:
        b.barrier_wait(9)
    for r in range(n * rounds):
        b = bs[r % n]
        b.mutex_lock(0)
        for ln in range(lines):
            addr = base + 64 * ln
            b.load(addr, 8)
            b.store(addr, 8)
        b.mutex_unlock(0)
    return TraceBatch.from_builders(bs)


def disjoint_stream(n, accesses=60):
    """Line-disjoint per-tile streams (capacity misses, no sharing)."""
    bs = [TraceBuilder() for _ in range(n)]
    for t, b in enumerate(bs):
        for i in range(accesses):
            addr = 0x100000 + (t * accesses + i) * 64
            (b.store if i % 3 == 0 else b.load)(addr, 8)
    return TraceBatch.from_builders(bs)


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_serialized_bit_exact_vs_oracle(proto):
    sc = make_config(4, proto)
    assert_exact(sc, mutex_rmw(4, rounds=6))


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_disjoint_concurrent_envelope(proto):
    """Line-disjoint CONCURRENT streams are exact under zero-load nets
    (test_memory_golden), but under hop_by_hop they contend for router
    ports, so the same-call batching contract applies (packets of one
    subquantum iteration see each other's occupancy only next iteration
    — `scatter_queue_delay` contract): measured 4.8%, pinned at 7%
    (BASELINE.md carve-outs; the USER net's adversarial case pins 15%).
    Counters stay exact — contention shifts time, never traffic."""
    sc = make_config(4, proto)
    batch = disjoint_stream(4)
    res = Simulator(sc, batch).run()
    gold = run_golden(sc, batch)
    rel = np.abs(res.clock_ps.astype(float) - gold.clock_ps.astype(float))
    rel = rel / np.maximum(gold.clock_ps.astype(float), 1.0)
    assert rel.max() <= 0.07, (
        f"divergence {rel.max():.4f}: engine={res.clock_ps.tolist()} "
        f"golden={gold.clock_ps.tolist()}")
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)
    assert int(gold.mem_counters["l2_misses"].sum()) > 0


def test_hbh_memory_changes_completion():
    """The contention-modeled memory net must produce different (higher)
    completion times than zero-load hop-counter under load — the silent
    hop_by_hop -> hop_counter degrade would make these equal."""
    batch = synthetic.memory_stress_trace(
        16, n_accesses=80, working_set_bytes=1 << 13,
        write_fraction=0.4, shared_fraction=0.5, seed=3)
    r_zero = Simulator(make_config(16, net="emesh_hop_counter"),
                       batch).run()
    r_hbh = Simulator(make_config(16, net="emesh_hop_by_hop"),
                      batch).run()
    assert r_hbh.completion_time_ps != r_zero.completion_time_ps
    # contention only ever adds latency on top of an identical zero-load
    # basis... but hop_by_hop's zero-load basis itself differs (router
    # charge + per-hop router+link on the SELF hop), so just require a
    # strictly larger completion under heavy shared traffic
    assert r_hbh.completion_time_ps > r_zero.completion_time_ps


def test_racy_envelope_vs_oracle():
    """Free-running shared traffic under the contention-modeled memory
    net compounds BOTH carve-outs (same-line race resolution ~3% +
    same-call port batching ~7%; BASELINE.md): measured 5.2%, pinned at
    their sum's ballpark, 8%."""
    sc = make_config(4, MSI)
    batch = synthetic.memory_stress_trace(
        4, n_accesses=150, working_set_bytes=1 << 13,
        write_fraction=0.4, shared_fraction=0.3, seed=5)
    res = Simulator(sc, batch).run()
    gold = run_golden(sc, batch)
    rel = np.abs(res.clock_ps.astype(float) - gold.clock_ps.astype(float))
    rel = rel / np.maximum(gold.clock_ps.astype(float), 1.0)
    assert rel.max() <= 0.08, (
        f"clock divergence {rel.max():.4f} exceeds envelope: "
        f"engine={res.clock_ps.tolist()} golden={gold.clock_ps.tolist()}")
    for k in ("l2_misses", "dram_reads"):
        e = int(np.asarray(res.mem_counters[k]).sum())
        g = int(gold.mem_counters[k].sum())
        assert abs(e - g) <= max(2, 0.02 * max(e, g)), f"{k}: {e} vs {g}"


ATAC_EXTRA = """
[network/atac]
flit_width = 64
cluster_size = 4
receive_network_type = star
global_routing_strategy = cluster_based
unicast_distance_threshold = 4
[network/atac/queue_model]
enabled = true
type = history_tree
[network/atac/enet/router]
delay = 1
[network/atac/onet/send_hub/router]
delay = 1
[network/atac/onet/receive_hub/router]
delay = 1
[network/atac/star_net/router]
delay = 1
[link_model/optical]
waveguide_delay_per_mm = 10e-3
E-O_conversion_delay = 1
O-E_conversion_delay = 1
"""


def test_atac_memory_serialized_bit_exact():
    """`[network] memory = atac` (any-model-per-net factory,
    `network.cc:21-40`): coherence messages ride the clusters/hubs/
    waveguide with hub contention on the memory NoC's own state.
    Serialized traffic is bit-exact vs the serial hub-queue oracle
    (`_AtacNet`), crossing clusters so the ONet path carries real
    protocol messages."""
    sc = make_config(16, MSI, net="atac", extra=ATAC_EXTRA)
    res, gold = assert_exact(sc, mutex_rmw(16, rounds=3, lines=2))
    assert int(np.asarray(res.mem_counters["l2_misses"]).sum()) > 0


def test_atac_memory_ackwise_broadcast_exact():
    """Overflowed-entry INV sweep under memory = atac: the broadcast
    charges the home's SEND HUB with its ONet copies and ranks every
    copy by tile id — mirrored exactly by `_AtacNet.fanout` on
    serialized traffic."""
    extra = ATAC_EXTRA + \
        "[dram_directory]\ndirectory_type = ackwise\nmax_hw_sharers = 2\n"
    sc = make_config(16, MSI, net="atac", extra=extra)
    bs = [TraceBuilder() for _ in range(16)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 16)
    for b in bs:
        b.barrier_wait(9)
    for t, b in enumerate(bs):
        b.mutex_lock(0)
        b.load(0x900000, 8)
        b.mutex_unlock(0)
    for b in bs:
        b.barrier_wait(9)
    # the writer sits in a DIFFERENT cluster than the home tile and
    # still holds the line: its own sweep copy and the cross-cluster
    # hub charge must match the oracle exactly (the engine's broadcast
    # row is holders | (all tiles except the requester))
    bs[10].mutex_lock(0)
    bs[10].store(0x900000, 8)
    bs[10].mutex_unlock(0)
    # follow-on cross-cluster traffic reads the hub queue the sweep
    # occupied — catches under-charged hub occupancy, not just arrivals
    for b in bs:
        b.barrier_wait(9)
    for t in (1, 5, 10, 15):
        bs[t].mutex_lock(0)
        bs[t].load(0x900000 + 64, 8)
        bs[t].mutex_unlock(0)
    res, gold = assert_exact(sc, TraceBatch.from_builders(bs))
    assert int(gold.mem_counters["dir_broadcasts"].sum()) > 0


def test_atac_memory_changes_timing():
    """The ATAC wiring is live: completion differs from the zero-load
    hop-counter memory net on the same workload."""
    batch = synthetic.memory_stress_trace(
        16, n_accesses=30, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=3)
    r_hc = Simulator(make_config(16, net="emesh_hop_counter"), batch).run()
    r_at = Simulator(make_config(16, net="atac", extra=ATAC_EXTRA),
                     batch).run()
    assert r_at.completion_time_ps != r_hc.completion_time_ps


def test_shl2_hbh_runs():
    """The shared-L2 engines route through the same contention net; smoke
    that the wiring compiles and produces traffic-dependent times."""
    batch = synthetic.memory_stress_trace(
        8, n_accesses=40, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=2)
    r_zero = Simulator(make_config(8, proto="pr_l1_sh_l2_msi",
                                   net="emesh_hop_counter"), batch).run()
    r_hbh = Simulator(make_config(8, proto="pr_l1_sh_l2_msi",
                                  net="emesh_hop_by_hop"), batch).run()
    assert r_hbh.completion_time_ps > r_zero.completion_time_ps


def test_ackwise_broadcast_fanout_exact():
    """Overflowed-entry INV sweep under the contention-modeled memory
    net: the broadcast occupies the home's inject port with T copies and
    each holder's copy ranks by tile id among ALL copies (engine's
    `send | over_bc` row).  Serialized (mutex-ordered) accesses keep it
    bit-exact vs the oracle, which mirrors the copy count and ranks
    (n_copies/ranks in `_HbhNet.fanout`)."""
    extra = "[dram_directory]\ndirectory_type = ackwise\nmax_hw_sharers = 2\n"
    sc = make_config(4, MSI, extra=extra)
    bs = [TraceBuilder() for _ in range(4)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 4)
    for b in bs:
        b.barrier_wait(9)
    # 4 readers (> max_hw_sharers=2 overflows the entry), serialized
    for t, b in enumerate(bs):
        b.mutex_lock(0)
        b.load(0x900000, 8)
        b.mutex_unlock(0)
    for b in bs:
        b.barrier_wait(9)
    # one writer: EX on the overflowed entry -> broadcast INV sweep
    bs[0].mutex_lock(0)
    bs[0].store(0x900000, 8)
    bs[0].mutex_unlock(0)
    res, gold = assert_exact(sc, TraceBatch.from_builders(bs))
    assert int(gold.mem_counters["dir_broadcasts"].sum()) > 0


def test_fanout_single_target_matches_unicast():
    """Formula self-consistency: a fan-out with exactly ONE target on an
    idle NoC must charge the same arrival time as the unicast path for
    that (src, dst) pair — the inject+rank approximation only diverges
    from per-hop routing when queues are occupied or k > 1.  Checked for
    both the hop-counter (zero-load closed form) and hop_by_hop nets."""
    import jax.numpy as jnp

    from graphite_tpu.memory.engine import mem_net_fanout, mem_net_send
    from graphite_tpu.models.network_hop_by_hop import init_noc_state

    batch = disjoint_stream(9, accesses=4)
    for net in ("emesh_hop_counter", "emesh_hop_by_hop"):
        sim = Simulator(make_config(9, net=net), batch)
        mp = sim.params.mem
        T = mp.n_tiles
        t0 = jnp.full((T,), 1_000_000, jnp.int64)
        for src, dst in ((0, 5), (4, 4), (8, 1)):
            noc = (None if mp.net_hbh is None
                   else init_noc_state(mp.net_hbh))
            send_hs = jnp.zeros((T, T), bool).at[src, dst].set(True)
            _, arr_fan = mem_net_fanout(mp, noc, send_hs, 128, t0, True)
            noc = (None if mp.net_hbh is None
                   else init_noc_state(mp.net_hbh))
            srcs = jnp.full((T,), src, jnp.int32)
            dsts = jnp.full((T,), dst, jnp.int32)
            mask = jnp.zeros((T,), bool).at[src].set(True)
            _, arr_uni = mem_net_send(
                mp, noc, srcs, dsts, 128, t0, mask, True)
            assert int(arr_fan[src, dst]) == int(arr_uni[src]), (
                net, src, dst)


def test_shl2_atac_memory_serialized_bit_exact():
    """The shared-L2 engine routes through the same mem_net_send, so
    `memory = atac` serves it too — serialized traffic bit-exact vs the
    shl2 oracle riding the same `_AtacNet`."""
    sc = make_config(16, proto="pr_l1_sh_l2_msi", net="atac",
                     extra=ATAC_EXTRA)
    res, gold = assert_exact(sc, mutex_rmw(16, rounds=3, lines=2))
    assert int(np.asarray(res.mem_counters["l2_misses"]).sum()) > 0


def test_shl2_atac_ackwise_broadcast_exact():
    """Shared-L2 overflowed-entry INV sweep under memory = atac: the
    shl2 engine's broadcast row (holders | all-except-requester) and hub
    charge mirror `memory_model_shl2`'s oracle exactly on serialized
    traffic — the writer sits in a different cluster than the home and
    still holds the line."""
    extra = ATAC_EXTRA + \
        "[dram_directory]\ndirectory_type = ackwise\nmax_hw_sharers = 2\n"
    sc = make_config(16, proto="pr_l1_sh_l2_msi", net="atac", extra=extra)
    bs = [TraceBuilder() for _ in range(16)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 16)
    for b in bs:
        b.barrier_wait(9)
    for t, b in enumerate(bs):
        b.mutex_lock(0)
        b.load(0x900000, 8)
        b.mutex_unlock(0)
    for b in bs:
        b.barrier_wait(9)
    bs[10].mutex_lock(0)
    bs[10].store(0x900000, 8)
    bs[10].mutex_unlock(0)
    for b in bs:
        b.barrier_wait(9)
    for t in (1, 5, 10, 15):
        bs[t].mutex_lock(0)
        bs[t].load(0x900000 + 64, 8)
        bs[t].mutex_unlock(0)
    res, gold = assert_exact(sc, TraceBatch.from_builders(bs))
    assert int(gold.mem_counters["dir_broadcasts"].sum()) > 0
