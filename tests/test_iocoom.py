"""IOCOOM core-model tests: scoreboard + load/store queue timing algebra.

Hand-derived from `iocoom_core_model.cc:79-276`:
 - a pure-ALU instruction advances the clock only to read_operands_ready
   (its execution overlaps younger instructions; `:240-248`);
 - register dependencies serialize through the scoreboard (`:115-146`);
 - a simple MOV load advances only to load_queue_ready; its write register
   is stamped LOAD_UNIT at completion+cost (`:185-198,246`);
 - a store advances to store_queue_ready (`:255-263`);
 - a load whose line sits in the store queue bypasses in one cycle
   (`executeLoad`, `isAddressAvailable`).

All tests run with enable_shared_mem=false: memory operand latencies are
zero, so queue timing is purely the one-cycle check costs — exactly
hand-computable.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=2):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[tile]
model_list = "<default,iocoom,T1,T1,T1>"
[core/iocoom]
num_load_queue_entries = 8
num_store_queue_entries = 8
speculative_loads_enabled = true
multiple_outstanding_RFOs_enabled = true
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
imul = 3
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax
"""
    return SimConfig(ConfigFile.from_string(text))


def run(sc, builders):
    return Simulator(sc, TraceBatch.from_builders(builders)).run()


class TestIocoomAlu:
    def test_independent_alus_fully_overlap(self):
        """Without dependencies the clock never advances: each instruction
        issues immediately (cost overlaps with younger instructions)."""
        b = TraceBuilder()
        for i in range(5):
            b.instr(Op.IALU, wreg=i)
        r = run(make_config(1), [b])
        assert r.clock_ps[0] == 0
        assert r.instruction_count[0] == 5

    def test_dependency_chain_serializes(self):
        """r1 = alu(); r2 = alu(r1); r3 = alu(r2): each waits one cost."""
        b = TraceBuilder()
        b.instr(Op.IALU, wreg=1)
        b.instr(Op.IALU, rregs=(1,), wreg=2)
        b.instr(Op.IALU, rregs=(2,), wreg=3)
        r = run(make_config(1), [b])
        # i2 issues at 1000 (r1 ready), i3 at 2000
        assert r.clock_ps[0] == 2000
        assert r.detailed_stalls["inter_ins_execution_unit"][0] == 2000

    def test_imul_dependency_costs_three_cycles(self):
        b = TraceBuilder()
        b.instr(Op.IMUL, wreg=1)
        b.instr(Op.IALU, rregs=(1,), wreg=2)
        r = run(make_config(1), [b])
        assert r.clock_ps[0] == 3000


class TestIocoomLoadStore:
    def test_simple_mov_load_overlaps(self):
        """A simple MOV load advances only to load-queue allocate (time 0);
        a dependent consumer waits for completion+cost via the LOAD_UNIT
        scoreboard entry."""
        b = TraceBuilder()
        b.load(0x100, wreg=1)                      # simple MOV load
        b.instr(Op.IALU, rregs=(1,), wreg=2)
        r = run(make_config(1), [b])
        # load: completion = 0 + (0 latency + 1cy SQ check) = 1000;
        # reg1 ready at completion + cost(mov 1cy) = 2000, LOAD_UNIT;
        # consumer: register_operands_ready = 2000
        assert r.clock_ps[0] == 2000
        assert r.detailed_stalls["inter_ins_l1dcache"][0] == 2000

    def test_store_advances_to_store_queue_ready(self):
        b = TraceBuilder()
        b.store(0x100)
        r = run(make_config(1), [b])
        # write_operands_ready = 0 + cost(1cy) = 1000; SQ allocate at 1000
        assert r.clock_ps[0] == 1000

    def test_load_bypasses_store_queue(self):
        """A load hitting a store-queue line returns in one cycle."""
        b = TraceBuilder()
        b.store(0x100)                             # SQ entry, dealloc 2000
        b.load(0x100, wreg=1)                      # bypass at sched 1000
        b.instr(Op.IALU, rregs=(1,), wreg=2)
        r = run(make_config(1), [b])
        # load: sched=1000 (clock after store), bypass completion 2000,
        # reg1 = 2000 + mov cost = 3000; consumer issues at 3000
        assert r.clock_ps[0] == 3000

    def test_load_queue_deallocate_serializes(self):
        """Speculative loads deallocate in order, one per cycle: N loads
        with zero latency still deallocate 1 cycle apart."""
        b = TraceBuilder()
        for i in range(4):
            b.load(0x100 + 64 * i, wreg=i)
        b.instr(Op.IALU, rregs=(3,), wreg=10)
        r = run(make_config(1), [b])
        # load k completes at 1000 but deallocates at max(1000, dealloc_{k-1}
        # +1000); reg_k = completion(1000) + cost(1000) = 2000 for every k
        # (completion, not dealloc, feeds the register) — consumer at 2000
        assert r.clock_ps[0] == 2000


class TestIocoomWithMemory:
    def test_cold_load_latency_reaches_scoreboard(self):
        """With the MSI protocol on, a cold load's full miss latency flows
        into the consumer's issue time through the LOAD_UNIT register."""
        text = """
[general]
total_cores = 1
mode = lite
enable_shared_mem = true
max_frequency = 1.0
[tile]
model_list = "<default,iocoom,T1,T1,T1>"
[network]
user = magic
memory = magic
[core/static_instruction_costs]
mov = 1
ialu = 1
[clock_skew_management]
scheme = lax
"""
        sc = SimConfig(ConfigFile.from_string(text))
        b = TraceBuilder()
        b.load(0x100, wreg=1)
        b.instr(Op.IALU, rregs=(1,), wreg=2)
        r = run(sc, [b])
        # consumer waits for the full cold-miss latency (directory + DRAM,
        # >= 100ns DRAM latency alone) + queue-check + cost cycles
        assert r.clock_ps[0] > 100_000
        assert r.mem_counters["l1d_read_misses"][0] == 1
        assert r.detailed_stalls["inter_ins_l1dcache"][0] == r.clock_ps[0]


class TestIocoomSummary:
    def test_summary_contains_detailed_breakdown(self):
        b = TraceBuilder()
        b.instr(Op.IALU, wreg=1)
        b.instr(Op.IALU, rregs=(1,), wreg=2)
        r = run(make_config(1), [b])
        s = r.summary()
        assert "Detailed Stall Time Breakdown" in s
        assert "Load Queue" in s


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
