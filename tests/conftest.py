"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip sharding (GSPMD specs over a Mesh) is tested on 8 virtual CPU
devices since only one real TPU chip is available; the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip.
Must run before jax initializes its backends, hence env vars here.
"""

import os

# Force CPU for the test suite (override any ambient tunnel platform like
# "axon"): tests validate semantics + sharding on the virtual 8-device CPU
# mesh; benches/entry points run on the real chip.  Set
# GRAPHITE_TESTS_PLATFORM to override.
os.environ["JAX_PLATFORMS"] = os.environ.get("GRAPHITE_TESTS_PLATFORM", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The ambient TPU-tunnel sitecustomize (axon) registers its backend and
# flips the platform config at interpreter startup, which wins over the
# JAX_PLATFORMS env var.  Flip it back explicitly: the suite must run on
# the virtual 8-device CPU mesh, not over the single-chip tunnel.
jax.config.update(
    "jax_platforms", os.environ.get("GRAPHITE_TESTS_PLATFORM", "cpu")
)

# Persistent compilation cache: the suite compiles ~40 engine topologies at
# ~15 s each; caching them across runs cuts the suite from ~10 min to ~2.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import graphite_tpu  # noqa: E402,F401  (enables x64)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy variants excluded from the tier-1 run (-m 'not slow')")
