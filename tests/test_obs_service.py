"""End-to-end campaign observability (round 14): the host metrics
registry, job-lifecycle span tracing, and their threading through the
campaign service.

The contract pins:
 - histograms are EXACT on a fake clock: deterministic fixed-bucket
   quantiles (first bucket reaching ceil(q*count)), hand-computed dwell
   values through the real service scheduling path;
 - every submitted job's span chain ends in exactly one terminal span
   (emit / reject / failed), across success, rejection, split/retry and
   exhausted-attempts paths;
 - `counters` is a pure compatibility view over the registry — one
   instrument per rate, identical keys to round 13;
 - exporters round-trip: Prometheus text parses back to the snapshot,
   span JSON-lines reload into the same per-job breakdown;
 - tracing/metrics are host-only: serve results are BIT-EQUAL with
   tracing on vs off (the device program never sees the tracer).
"""

import io
import json

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS, EnergyPrices, Histogram, MetricsError,
    MetricsRegistry, TERMINAL_SPANS, Tracer, job_breakdown,
    parse_exposition,
)
from graphite_tpu.obs.trace import load_jsonl
from graphite_tpu.serve import CampaignService, Job, JobResult, \
    QueueFullError, STATUS_OK
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

TILES = 4


class FakeClock:
    """Monotonic seconds under test control."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _config(clock="lax", tiles=TILES):
    return SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme=clock)))


def _trace(seed, n=8, tiles=TILES):
    return synthetic.memory_stress_trace(
        tiles, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _bucket_of(v):
    """The deterministic quantile answer for an observation `v` under
    the default latency buckets (first bound >= v)."""
    return min(b for b in DEFAULT_LATENCY_BUCKETS if b >= v)


# ---------------------------------------------------------------------------
# metrics primitives
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_hand_computed_quantiles(self):
        """Exactness on a hand-built observation set: quantile(q) is
        the upper bound of the first bucket whose cumulative count
        reaches ceil(q * count)."""
        h = Histogram("h", buckets=(1, 2, 4, 8))
        for v in (0.5, 1.0, 3.0, 3.0, 5.0, 7.0):
            h.observe(v)
        # counts per bucket: le=1 -> 2, le=2 -> 0, le=4 -> 2, le=8 -> 2
        assert h.counts == [2, 0, 2, 2, 0]
        assert h.count == 6 and h.sum == 19.5
        assert h.quantile(0.5) == 4    # rank 3 -> cum 2,2,4 -> le=4
        assert h.quantile(1 / 3) == 1  # rank 2 -> first bucket
        assert h.quantile(0.9) == 8    # rank 6
        assert h.quantile(1.0) == 8
        assert h.min == 0.5 and h.max == 7.0

    def test_overflow_bucket_resolves_to_true_max(self):
        h = Histogram("h", buckets=(1, 2))
        h.observe(0.5)
        h.observe(100.0)
        assert h.counts == [1, 0, 1]
        assert h.quantile(0.5) == 1
        assert h.quantile(1.0) == 100.0   # +Inf bucket -> exact max

    def test_empty_and_validation(self):
        h = Histogram("h", buckets=(1, 2))
        assert h.quantile(0.5) == 0.0 and h.mean == 0.0
        assert h.min == 0.0 and h.max == 0.0
        with pytest.raises(MetricsError, match="ascending"):
            Histogram("bad", buckets=(2, 1))
        with pytest.raises(MetricsError, match="implicit"):
            Histogram("bad", buckets=(1, float("inf")))
        with pytest.raises(MetricsError, match="outside"):
            h.quantile(0.0)


class TestRegistry:
    def test_get_or_create_and_type_conflict(self):
        reg = MetricsRegistry()
        c = reg.counter("a", "help")
        assert reg.counter("a") is c
        with pytest.raises(MetricsError, match="already registered"):
            reg.gauge("a")
        # two sites disagreeing on a histogram's bucket layout must
        # fail fast, not silently observe into the wrong buckets
        h = reg.histogram("h", buckets=(1, 2))
        assert reg.histogram("h", buckets=(1, 2)) is h
        with pytest.raises(MetricsError, match="buckets"):
            reg.histogram("h", buckets=(1, 2, 4))
        with pytest.raises(MetricsError, match="cannot decrease"):
            c.inc(-1)
        with pytest.raises(MetricsError, match="unknown metric"):
            reg["nope"]

    def test_snapshot_shapes(self):
        reg = MetricsRegistry()
        reg.counter("c").inc(2)
        reg.gauge("g").set(1.5)
        h = reg.histogram("h", buckets=(1, 10))
        h.observe(3)
        snap = reg.snapshot()
        assert snap["c"] == 2 and snap["g"] == 1.5
        assert snap["h"]["count"] == 1 and snap["h"]["p50"] == 10
        assert snap["h"]["sum"] == 3.0

    def test_exposition_round_trip(self):
        """Prometheus text -> parse_exposition recovers every counter,
        gauge, and histogram bucket/sum/count exactly."""
        reg = MetricsRegistry()
        reg.counter("jobs_total", "jobs").inc(7)
        reg.gauge("depth").set(3)
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1))
        for v in (0.005, 0.5, 0.5, 2.0):
            h.observe(v)
        back = parse_exposition(reg.exposition())
        assert back["jobs_total"] == {"type": "counter", "value": 7}
        assert back["depth"] == {"type": "gauge", "value": 3}
        hist = back["lat_seconds"]
        assert hist["type"] == "histogram"
        assert hist["buckets"] == {"0.01": 1, "0.1": 1, "1": 3,
                                   "+Inf": 4}
        assert hist["count"] == 4 and hist["sum"] == pytest.approx(3.005)
        with pytest.raises(MetricsError, match="unknown metric"):
            parse_exposition("rogue_metric 1\n")

    def test_timeline_sampling_fake_clock(self):
        clk = FakeClock(10.0)
        reg = MetricsRegistry(clock=clk, max_timeline=2)
        c = reg.counter("n")
        for i in range(3):
            c.inc()
            clk.advance(1.0)
            reg.sample()
        # bounded: keeps the newest 2 rows, timestamps from the clock
        assert len(reg.timeline) == 2
        assert [row["t_s"] for row in reg.timeline] == [12.0, 13.0]
        assert [row["n"] for row in reg.timeline] == [2, 3]
        rows = [json.loads(ln) for ln
                in reg.timeline_jsonl().splitlines()]
        assert rows == list(reg.timeline)


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


class TestTracer:
    def test_span_lifecycle_fake_clock(self):
        clk = FakeClock(5.0)
        tr = Tracer(clock=clk)
        s = tr.begin("j0", "submit", seed=3)
        clk.advance(0.25)
        tr.end(s, ok=True)
        assert s.dur_s == 0.25 and s.attrs == {"seed": 3, "ok": True}
        tr.event("j0", "emit")
        rows = tr.to_rows()
        # timestamps are epoch-relative integer microseconds
        assert rows[0] == {"trace": "j0", "span": "submit",
                           "start_us": 0, "dur_us": 250000,
                           "seed": 3, "ok": True}
        assert rows[1]["start_us"] == 250000 and rows[1]["dur_us"] == 0

    def test_record_and_missing_terminal(self):
        tr = Tracer(clock=FakeClock())
        tr.record("j0", "queue", 1.0, 3.5, batch=0)
        tr.event("j0", "emit")
        tr.event("j1", "reject")
        tr.event("j2", "split")   # not terminal
        assert tr.trace("j0")[0].dur_s == 2.5
        assert tr.missing_terminal(["j0", "j1", "j2"]) == ["j2"]
        assert set(TERMINAL_SPANS) == {"emit", "reject", "failed"}

    def test_export_load_breakdown_round_trip(self):
        clk = FakeClock()
        tr = Tracer(clock=clk)
        with tr.span("j0", "submit"):
            clk.advance(0.5)
        tr.record("j0", "queue", clk.t, clk.t + 2.0)
        tr.record("batch-0", "batch", 0.0, 1.0, ok=True)
        tr.event("j0", "emit", batch=0, attempts=1)
        buf = io.StringIO()
        assert tr.export_jsonl(buf) == 4
        buf.seek(0)
        rows = load_jsonl(buf)
        assert len(rows) == 4
        (bd,) = job_breakdown(rows)   # batch-* excluded
        assert bd["job"] == "j0" and bd["status"] == "emit"
        assert bd["submit_us"] == 500000 and bd["queue_us"] == 2000000
        assert bd["total_us"] == 2500000
        assert bd["attempts"] == 1


# ---------------------------------------------------------------------------
# service threading (stubbed execution — no compiles, fake clock)
# ---------------------------------------------------------------------------


def _stub_ok(svc):
    def execute(cls, pendings, batch_id):
        return [JobResult(job_id=p.job.job_id, status=STATUS_OK,
                          batch_id=batch_id, attempts=p.attempts + 1)
                for p in pendings]
    return execute


class TestServiceObservability:
    def test_dwell_histogram_exact_on_fake_clock(self, monkeypatch):
        """Hand-computed queue dwell through the real scheduling path:
        enqueue timestamps, batch-form pop, histogram observation."""
        clk = FakeClock()
        svc = CampaignService(batch_size=4, tracing=True, clock=clk)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        svc.submit(Job("j0", _config(), _trace(1)))
        clk.advance(1.75)
        svc.submit(Job("j1", _config(), _trace(2)))
        clk.advance(0.25)
        out = svc.run_all()
        assert [r.job_id for r in out] == ["j0", "j1"]
        h = svc.metrics["queue_dwell_seconds"]
        # exact: j0 waited 2.0 s, j1 0.25 s (binary-exact floats)
        assert h.count == 2 and h.sum == 2.25
        assert h.max == 2.0 and h.min == 0.25
        assert h.quantile(0.5) == _bucket_of(0.25)
        assert h.quantile(1.0) == _bucket_of(2.0)
        # the envelopes carry the same dwell
        assert out[0].timings["queue_dwell_s"] == 2.0
        assert out[1].timings["queue_dwell_s"] == 0.25
        # and the reconstructed queue spans match exactly
        qs = [s for s in svc.tracer.trace("j0") if s.name == "queue"]
        assert len(qs) == 1 and qs[0].dur_s == 2.0

    def test_span_chain_complete_and_ordered(self, monkeypatch):
        svc = CampaignService(batch_size=2, tracing=True,
                              clock=FakeClock())
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        for i in range(3):
            svc.submit(Job(f"j{i}", _config(), _trace(i + 1)))
        svc.run_all()
        assert svc.tracer.missing_terminal(
            ["j0", "j1", "j2"]) == []
        # the stub bypasses _execute, so no per-job execute span here
        # (the end-to-end test asserts the full chain)
        names = [s.name for s in svc.tracer.trace("j0")]
        assert names == ["validate", "admit", "submit", "queue", "emit"]
        # batch spans carry the execution bookkeeping
        batches = [s for s in svc.tracer.spans if s.name == "batch"]
        assert len(batches) == 2
        assert batches[0].attrs["capacity"] == 2
        assert batches[0].attrs["n_jobs"] == 2
        assert batches[0].attrs["ok"] is True
        assert "class" in batches[0].attrs

    def test_reject_and_backpressure_spans(self):
        svc = CampaignService(batch_size=2, max_pending=1, tracing=True,
                              clock=FakeClock())
        with pytest.raises(ValueError):
            svc.submit(Job("bad", _config(tiles=8), _trace(1)))
        assert svc.tracer.missing_terminal(["bad"]) == []
        assert svc.counters["rejected"] == 1
        svc.submit(Job("ok0", _config(), _trace(1)))
        with pytest.raises(QueueFullError):
            svc.submit(Job("ok1", _config(), _trace(2)))
        assert svc.counters["backpressure"] == 1
        bp = [s for s in svc.tracer.spans if s.name == "backpressure"]
        assert len(bp) == 1 and bp[0].trace_id == "ok1"
        # backpressure is NOT terminal — the job never entered the queue
        assert svc.tracer.missing_terminal(["ok1"]) == ["ok1"]

    def test_split_retry_and_failed_terminal_spans(self, monkeypatch):
        from graphite_tpu.engine.simulator import DeadlockError

        svc = CampaignService(batch_size=4, max_attempts=2,
                              tracing=True, clock=FakeClock())

        def always_fail(cls, pendings, batch_id):
            raise DeadlockError("stuck")

        monkeypatch.setattr(svc, "_execute", always_fail)
        for i in range(2):
            svc.submit(Job(f"j{i}", _config(), _trace(i + 1)))
        out = svc.run_all()
        assert all(not r.ok for r in out) and len(out) == 2
        assert svc.tracer.missing_terminal(["j0", "j1"]) == []
        assert svc.counters["splits"] == 1
        # split depth histogram: both jobs consumed max_attempts
        h = svc.metrics["split_depth"]
        assert h.count == 2 and h.sum == 4.0
        # failed batch spans are recorded with ok=False
        bad = [s for s in svc.tracer.spans
               if s.name == "batch" and not s.attrs["ok"]]
        assert len(bad) == 3   # 1 full batch + 2 singleton retries
        assert all("DeadlockError" in s.attrs["error"] for s in bad)

    def test_requeue_restarts_dwell_clock(self, monkeypatch):
        """A split member's second wait is a second observation from
        the requeue time, not a longer first one."""
        from graphite_tpu.engine.simulator import DeadlockError

        clk = FakeClock()
        svc = CampaignService(batch_size=2, max_attempts=3,
                              tracing=True, clock=clk)
        calls = {"n": 0}

        def fail_once(cls, pendings, batch_id):
            calls["n"] += 1
            if calls["n"] == 1:
                raise DeadlockError("first batch only")
            return _stub_ok(svc)(cls, pendings, batch_id)

        monkeypatch.setattr(svc, "_execute", fail_once)
        svc.submit(Job("j0", _config(), _trace(1)))
        svc.submit(Job("j1", _config(), _trace(2)))
        svc.run_all()
        h = svc.metrics["queue_dwell_seconds"]
        # 2 first waits + 2 post-split waits (fake clock: all zero)
        assert h.count == 4
        assert svc.counters["completed"] == 2

    def test_caller_owned_tracer_shares_the_service_timebase(
            self, monkeypatch):
        """A caller-owned Tracer must not mix timebases with the
        service clock: reconstructed spans (queue dwell) carry
        service-clock timestamps, so the two are reconciled at
        construction."""
        from graphite_tpu.engine.simulator import DeadlockError

        clk = FakeClock(100.0)
        tr = Tracer()   # caller default clock — service clock wins
        svc = CampaignService(batch_size=2, max_attempts=1,
                              tracing=tr, clock=clk)
        assert svc.tracer is tr and tr.clock is clk
        # no explicit clock: the service adopts the tracer's clock
        clk2 = FakeClock(7.0)
        svc2 = CampaignService(tracing=Tracer(clock=clk2))
        assert svc2._clock is clk2

        def fail(cls, pendings, batch_id):
            clk.advance(2.0)   # execution takes 2 s on the fake clock
            raise DeadlockError("x")

        monkeypatch.setattr(svc, "_execute", fail)
        svc.submit(Job("j0", _config(), _trace(1)))
        svc.run_all()
        # the failed-batch span covers the REAL execute window
        # (t0, t0 + wall), unshifted by later metric clock reads
        (bspan,) = [s for s in tr.spans if s.name == "batch"]
        assert bspan.dur_s == 2.0
        (qspan,) = [s for s in tr.trace("j0") if s.name == "queue"]
        assert qspan.t_end == bspan.t_start

    def test_counters_is_registry_view(self, monkeypatch):
        svc = CampaignService(batch_size=2, clock=FakeClock())
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        assert svc.tracer is None   # tracing defaults off
        for i in range(3):
            svc.submit(Job(f"j{i}", _config(), _trace(i + 1)))
        out = svc.run_all()
        assert len(out) == 3 and all(r.timings is None for r in out)
        c = svc.counters
        m = svc.metrics
        assert c["submitted"] == m["jobs_submitted_total"].value == 3
        assert c["completed"] == m["jobs_completed_total"].value == 3
        assert c["batches"] == m["batches_total"].value == 2
        assert c["mean_batch_occupancy"] == \
            m["batch_occupancy"].mean == pytest.approx(0.75)
        # identity: submitted == completed + failed
        assert c["submitted"] == c["completed"] + c["failed"]
        # metrics timeline sampled once per batch
        assert len(m.timeline) == 2


# ---------------------------------------------------------------------------
# energy spec plumbing (no compiles)
# ---------------------------------------------------------------------------


class TestEnergySpec:
    def test_prices_validation(self):
        with pytest.raises(ValueError, match="non-negative integer"):
            EnergyPrices(instruction_pj=-1)
        with pytest.raises(ValueError, match="non-negative integer"):
            EnergyPrices(l2_miss_pj=1.5)
        assert EnergyPrices(l2_miss_pj=3).needs_mem()
        assert not EnergyPrices(instruction_pj=3,
                                packet_pj=1).needs_mem()

    def test_energy_series_needs_prices(self):
        from graphite_tpu.engine.simulator import Simulator
        from graphite_tpu.obs import TelemetrySpec

        sim = Simulator(_config(), _trace(1))
        with pytest.raises(ValueError, match="energy_prices"):
            TelemetrySpec(sample_interval_ps=1,
                          series=("energy_pj",)).resolve(sim.params)
        spec = TelemetrySpec(
            sample_interval_ps=1, series=("energy_pj",),
            energy_prices=EnergyPrices(instruction_pj=1)).resolve(
                sim.params)
        assert spec.series == ("time_ps", "energy_pj")
        # dense selection includes energy exactly when prices are given
        dense_off = TelemetrySpec(sample_interval_ps=1).resolve(
            sim.params)
        dense_on = TelemetrySpec(
            sample_interval_ps=1,
            energy_prices=EnergyPrices()).resolve(sim.params)
        assert "energy_pj" not in dense_off.series
        assert dense_on.series == dense_off.series + ("energy_pj",)

    def test_memoryless_rejects_mem_prices(self):
        from graphite_tpu.engine.simulator import Simulator
        from graphite_tpu.obs import TelemetrySpec

        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, clock_scheme="lax")))
        batch = synthetic.message_ring_batch(TILES, n_rounds=2,
                                             compute_per_round=4)
        sim = Simulator(sc, batch)
        with pytest.raises(ValueError, match="no memory subsystem"):
            TelemetrySpec(
                sample_interval_ps=1,
                energy_prices=EnergyPrices(l2_miss_pj=5)).resolve(
                    sim.params)
        # instruction/packet-only prices are fine on memoryless traces
        spec = TelemetrySpec(
            sample_interval_ps=1,
            energy_prices=EnergyPrices(instruction_pj=2)).resolve(
                sim.params)
        assert "energy_pj" in spec.series

    def test_class_key_splits_on_energy_prices(self):
        from graphite_tpu.obs import TelemetrySpec
        from graphite_tpu.serve import AdmissionController

        adm = AdmissionController()
        t = _trace(1)
        base = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=16)
        priced = TelemetrySpec(
            sample_interval_ps=1_000_000, n_samples=16,
            energy_prices=EnergyPrices(instruction_pj=2))
        priced2 = TelemetrySpec(
            sample_interval_ps=1_000_000, n_samples=16,
            energy_prices=EnergyPrices(instruction_pj=9))
        keys = {adm.class_key(Job("a", _config(), t, telemetry=s))
                for s in (base, priced, priced2)}
        # different prices lower different literals -> never co-batch
        assert len(keys) == 3

    def test_from_power_model_integer_prices(self):
        prices = EnergyPrices.from_power_model(45)
        for f in ("instruction_pj", "l1d_access_pj", "l2_access_pj",
                  "l2_miss_pj", "dram_access_pj", "packet_pj"):
            v = getattr(prices, f)
            assert isinstance(v, int) and v > 0, f
        # bigger node -> no cheaper events (sanity on the native model)
        p90 = EnergyPrices.from_power_model(90)
        assert p90.dram_access_pj >= prices.dram_access_pj


# ---------------------------------------------------------------------------
# end-to-end: tracing on/off bit-equality + CLI renderers (one compile)
# ---------------------------------------------------------------------------


class TestEndToEnd:
    def test_tracing_on_off_bit_equal_and_exporters(self, tmp_path):
        from graphite_tpu.tools.report import main as report_main

        jobs = [("j0", 1), ("j1", 2), ("j2", 3)]

        def run(tracing):
            svc = CampaignService(batch_size=2, max_quanta=200_000,
                                  tracing=tracing)
            for jid, seed in jobs:
                svc.submit(Job(jid, _config(), _trace(seed), seed=seed))
            return svc, {r.job_id: r for r in svc.drain()}

        svc_off, off = run(False)
        svc_on, on = run(True)
        for jid, _ in jobs:
            a, b = off[jid].results, on[jid].results
            np.testing.assert_array_equal(a.clock_ps, b.clock_ps)
            np.testing.assert_array_equal(a.instruction_count,
                                          b.instruction_count)
            for k in a.mem_counters:
                np.testing.assert_array_equal(
                    a.mem_counters[k], b.mem_counters[k], err_msg=k)
            assert on[jid].timings is not None
            assert off[jid].timings is None
        assert svc_on.tracer.missing_terminal(
            [j for j, _ in jobs]) == []
        # the full lifecycle chain, in order, on the real execute path
        assert [s.name for s in svc_on.tracer.trace("j0")] == \
            ["validate", "admit", "submit", "queue", "execute", "emit"]

        # span export -> report --spans (text + json)
        spath = str(tmp_path / "spans.jsonl")
        assert svc_on.export_spans(spath) > 0
        assert report_main(["--spans", spath, "--format", "text"]) == 0
        assert report_main(["--spans", spath]) == 0
        # metrics export -> report --metrics
        mpath = str(tmp_path / "metrics.prom")
        with open(mpath, "w") as fh:
            fh.write(svc_on.metrics.exposition())
        assert report_main(["--metrics", mpath,
                            "--format", "text"]) == 0
        back = parse_exposition(open(mpath).read())
        assert back["jobs_completed_total"]["value"] == 3
        assert back["queue_dwell_seconds"]["count"] == 3

    def test_report_modes_are_exclusive(self, capsys):
        from graphite_tpu.tools.report import main as report_main

        with pytest.raises(SystemExit):
            report_main([])
        with pytest.raises(SystemExit):
            report_main(["x.npz", "--spans", "y.jsonl"])
        capsys.readouterr()
