"""Host->HBM windowed trace streaming (`Simulator(stream=True)` +
`run_streamed`): results must be bit-identical to the all-resident
replay — pausing lanes at a window edge is wall-time only.

Reference analog: Pin streams instructions continuously
(`pin/instruction_modeling.cc:13-21`); the all-resident mode is this
engine's own addition.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import DeadlockError, Simulator
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles, shared_mem=False):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = {str(shared_mem).lower()}
[network]
user = magic
memory = magic
[core/static_instruction_costs]
ialu = 1
imul = 3
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def assert_stream_matches(sc, batch, window):
    ref = Simulator(sc, batch).run()
    res = Simulator(sc, batch, stream=True).run_streamed(
        window_records=window)
    np.testing.assert_array_equal(ref.clock_ps, res.clock_ps)
    np.testing.assert_array_equal(ref.instruction_count,
                                  res.instruction_count)
    return res


def test_stream_compute_windows():
    """Windows much smaller than the trace; lockstep lanes."""
    bs = [TraceBuilder() for _ in range(4)]
    for i in range(500):
        for b in bs:
            b.instr(Op.IALU if i % 3 else Op.IMUL)
    assert_stream_matches(make_config(4), TraceBatch.from_builders(bs), 64)


def test_stream_messaging_across_windows():
    """Ring messaging with recv dependencies spanning window slides."""
    batch = synthetic.message_ring_batch(4, n_rounds=40,
                                         compute_per_round=11)
    assert_stream_matches(make_config(4), batch, 48)


def test_stream_diverged_lanes():
    """One tile's stream is much longer: the laggard window must follow
    the slowest lane while leaders pause at the edge."""
    bs = [TraceBuilder() for _ in range(2)]
    for i in range(40):
        bs[0].instr(Op.IALU)
    for i in range(400):
        bs[1].instr(Op.IALU)
    bs[0].barrier_init(0, 2)
    for b in bs:
        b.barrier_wait(0)
    assert_stream_matches(make_config(2), TraceBatch.from_builders(bs), 64)


def test_stream_memory_engine():
    """Coherence state carries across window slides."""
    sc = make_config(2, shared_mem=True)
    batch = synthetic.memory_stress_trace(
        2, n_accesses=120, working_set_bytes=1 << 14, seed=9)
    ref = Simulator(sc, batch).run()
    res = Simulator(sc, batch, stream=True).run_streamed(window_records=32)
    np.testing.assert_array_equal(ref.clock_ps, res.clock_ps)
    for k in ref.mem_counters:
        np.testing.assert_array_equal(np.asarray(ref.mem_counters[k]),
                                      np.asarray(res.mem_counters[k]), k)


def test_stream_unbounded_skew():
    """Per-tile window bases admit arbitrary lane skew: tile 0 joins a
    tile whose exit lies many windows ahead of tile 0's own stream."""
    bs = [TraceBuilder() for _ in range(2)]
    bs[0].thread_join(1)
    for i in range(300):
        bs[1].instr(Op.IALU)
    batch = TraceBatch.from_builders(bs)
    assert_stream_matches(make_config(2), batch, 64)


def test_stream_detects_real_deadlock():
    """A genuine deadlock (join on a tile that never exits... here: a
    mutex locked and never released) still raises under streaming."""
    bs = [TraceBuilder() for _ in range(2)]
    bs[0].mutex_init(0)
    bs[0].mutex_lock(0)
    for i in range(10):
        bs[0].instr(Op.IALU)
    bs[1].mutex_lock(0)   # never granted: tile 0 exits holding the lock
    with pytest.raises(DeadlockError):
        Simulator(make_config(2), TraceBatch.from_builders(bs),
                  stream=True).run_streamed(window_records=32)


def test_streamed_sharded_matches_streamed_single():
    """Streaming composes with sharding: a streamed coherence run on the
    8-device mesh must be bit-identical to the streamed single-device
    run with the same window size (the two scale mechanisms — bounded-
    HBM windows and multi-chip tile striping — now combine).  The
    comparison is streamed-vs-streamed: window pausing changes racy
    interleavings vs the resident run (documented race contract), so the
    resident run is not the right oracle for a free-running shared-line
    workload; what sharding must never change is the computation itself."""
    from graphite_tpu.parallel.mesh import make_tile_mesh
    from graphite_tpu.tools._template import coherence_stress_workload

    sc, batch = coherence_stress_workload(64, n_accesses=30)
    ref = Simulator(sc, batch, stream=True).run_streamed(window_records=16)

    mesh = make_tile_mesh(8)
    sim = Simulator(sc, batch, mesh=mesh, stream=True)
    res = sim.run_streamed(window_records=16)
    np.testing.assert_array_equal(ref.clock_ps, res.clock_ps)
    np.testing.assert_array_equal(ref.instruction_count,
                                  res.instruction_count)
    for k, v in ref.mem_counters.items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(res.mem_counters[k]), err_msg=k)
    assert int(np.asarray(ref.mem_counters["l2_misses"]).sum()) > 0
