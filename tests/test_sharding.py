"""Multi-chip sharding: tile axis over a virtual 8-device CPU mesh.

The sharded quantum step must produce bit-identical results to the
single-device run (determinism is the TPU build's replacement for the
reference's manual thread-safety — SURVEY §5 race detection).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.engine.step import run_quantum
from graphite_tpu.parallel.mesh import make_tile_mesh, shard_sim
from graphite_tpu.trace import synthetic

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _make_sim(n_tiles=64, **kw):
    cfg = f"""
[general]
total_cores = {n_tiles}
mode = lite
[network]
user = emesh_hop_counter
memory = emesh_hop_counter
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax
"""
    sc = SimConfig(ConfigFile.from_string(cfg))
    batch = synthetic.message_ring_batch(n_tiles, n_rounds=3,
                                         compute_per_round=8)
    return Simulator(sc, batch, **kw)


def test_sharded_matches_single_device():
    sim_a = _make_sim(64)
    ra = sim_a.run()

    mesh = make_tile_mesh(8)
    sim_b = _make_sim(64, mesh=mesh)
    rb = sim_b.run()

    assert ra.clock_ps.tolist() == rb.clock_ps.tolist()
    assert ra.instruction_count.tolist() == rb.instruction_count.tolist()
    assert ra.total_packet_latency_ps.tolist() == rb.total_packet_latency_ps.tolist()


# ---- coherence engine under sharding --------------------------------------
# The defining distributed path of the reference is cross-process coherence
# (`memory_manager.cc:237-303` over `socktransport.cc`); its TPU-native
# equivalent is the MSI/MOSI/shL2 engine's [T, T] mailbox matrices crossing
# shard boundaries.  These tests run the SAME coherence workload sharded over
# 8 devices and single-device and require bit-identical clocks AND memory
# counters (determinism replaces the reference's manual thread-safety).

MSI = "pr_l1_pr_l2_dram_directory_msi"
MOSI = "pr_l1_pr_l2_dram_directory_mosi"
SHL2_MSI = "pr_l1_sh_l2_msi"
SHL2_MESI = "pr_l1_sh_l2_mesi"


def _make_mem_sim(n_tiles=64, proto=MSI, mesh=None, spmd=None):
    from graphite_tpu.tools._template import coherence_stress_workload

    sc, batch = coherence_stress_workload(n_tiles, protocol=proto)
    return Simulator(sc, batch, mesh=mesh, spmd=spmd)


@pytest.mark.parametrize("proto", [MSI, MOSI, SHL2_MSI, SHL2_MESI])
def test_sharded_coherence_matches_single_device(proto):
    # every protocol — private-L2 AND shared-L2 — rides the packed
    # shard_map exchange by default and must be bit-identical to the
    # single-device run
    ra = _make_mem_sim(proto=proto).run()
    rb = _make_mem_sim(proto=proto, mesh=make_tile_mesh(8)).run()

    np.testing.assert_array_equal(ra.clock_ps, rb.clock_ps,
                                  err_msg="clocks diverge under sharding")
    np.testing.assert_array_equal(
        ra.instruction_count, rb.instruction_count)
    assert ra.mem_counters is not None and rb.mem_counters is not None
    for k, va in ra.mem_counters.items():
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(rb.mem_counters[k]),
            err_msg=f"mem counter {k} diverges under sharding")
    assert ra.func_errors == 0 and rb.func_errors == 0
    # vacuity guard: the equality above must be over real protocol traffic
    assert int(np.asarray(ra.mem_counters["l2_misses"]).sum()) > 0


def test_default_mesh_program_selection():
    # shard_map is the default multi-chip program for EVERY protocol
    # (the shared-L2 engine took the exchange context in round 5)
    mesh = make_tile_mesh(8)
    assert _make_mem_sim(proto=MSI, mesh=mesh).spmd == "shard_map"
    assert _make_mem_sim(proto=SHL2_MSI, mesh=mesh).spmd == "shard_map"
    assert _make_sim(64, mesh=mesh).spmd == "shard_map"


def test_gspmd_coherence_still_matches_single_device():
    # the legacy whole-program-partitioning path stays available (and
    # bit-identical) behind spmd="gspmd"
    ra = _make_mem_sim(proto=MSI).run()
    rb = _make_mem_sim(proto=MSI, mesh=make_tile_mesh(8),
                       spmd="gspmd").run()
    np.testing.assert_array_equal(ra.clock_ps, rb.clock_ps)
    np.testing.assert_array_equal(ra.instruction_count, rb.instruction_count)


def test_sharded_coherence_state_layout():
    sim = _make_mem_sim()
    mesh = make_tile_mesh(8)
    state, _ = shard_sim(sim.state, sim.device_trace, mesh)
    # per-tile rows sharded; the [T, T] mailbox matrices shard on their
    # owner axis (row 0 = the consuming side); functional memory replicated
    assert "tiles" in str(state.mem.l1d.meta.sharding)
    assert "tiles" in str(state.mem.mail.req_type.sharding)
    assert "tiles" in str(state.mem.mail.fwd_type.sharding)
    assert state.mem.func_mem.sharding.is_fully_replicated


def test_state_sharding_layout():
    sim = _make_sim(64)
    mesh = make_tile_mesh(8)
    state, trace = shard_sim(sim.state, sim.device_trace, mesh)
    # tile-major arrays sharded, sync tables replicated
    assert "tiles" in str(state.core.clock_ps.sharding)
    assert "tiles" in str(state.net.time_ps.sharding)
    assert state.sync.barrier_count.sharding.is_fully_replicated
    assert "tiles" in str(trace.op.sharding)


def test_indivisible_tile_count_rejected():
    sim = _make_sim(36)  # 6x6 mesh, not divisible by 8
    mesh = make_tile_mesh(8)
    with pytest.raises(ValueError, match="divisible"):
        shard_sim(sim.state, sim.device_trace, mesh)
