"""Shared-L2 protocol tests (pr_l1_sh_l2_msi / pr_l1_sh_l2_mesi).

Private L1s, distributed shared L2 with embedded directory: an L1 miss goes
to the line's home slice; a slice miss fetches from DRAM (DATA_INVALID);
MESI grants EXCLUSIVE on a lone read and upgrades E→M silently.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=2, protocol="pr_l1_sh_l2_msi"):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[caching_protocol]
type = {protocol}
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def run(sc, builders, **kw):
    return Simulator(sc, TraceBatch.from_builders(builders), **kw).run()


class TestShL2MSI:
    def test_single_tile_store_load(self):
        sc = make_config(1)
        b = TraceBuilder()
        b.store_value(0x40, 7)
        b.load_check(0x40, 7)
        r = run(sc, [b])
        assert r.func_errors == 0
        mc = r.mem_counters
        assert mc["l1d_write_misses"][0] == 1
        assert mc["l1d_read_hits"][0] == 1      # second access hits L1
        assert mc["dram_reads"].sum() == 1      # one slice fill

    def test_producer_consumer(self):
        """Write on tile 0, read on tile 1 (line homed somewhere): the
        value propagates through the shared slice."""
        sc = make_config(2)
        addr = 0x40                    # line 1 -> home tile 1
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 42)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 42)
        r = run(sc, [b0, b1])
        assert r.func_errors == 0
        # tile 1's read flushed tile 0's M copy through the home slice
        assert r.mem_counters["dram_reads"].sum() == 1

    def test_write_invalidation_ping_pong(self):
        sc = make_config(2)
        addr = 0x0
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 1)
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.load_check(addr, 2)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.store_value(addr, 2)
        b1.barrier_wait(0)
        r = run(sc, [b0, b1])
        assert r.func_errors == 0
        # two tiles alternating writes: the M copy is flushed each time
        # (INV only happens with >1 sharer — see test_four_tiles_one_line)

    def test_read_sharers_then_upgrade(self):
        sc = make_config(2)
        addr = 0x40
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.load_check(addr, 0)
        b0.barrier_wait(0)
        b0.store_value(addr, 5)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.load_check(addr, 0)
        b1.barrier_wait(0)
        b1.barrier_wait(0)
        b1.load_check(addr, 5)
        r = run(sc, [b0, b1])
        assert r.func_errors == 0

    def test_four_tiles_one_line(self):
        sc = make_config(4)
        addr = 0x80
        builders = []
        for t in range(4):
            b = TraceBuilder()
            if t == 0:
                b.barrier_init(0, 4)
                b.store_value(addr, 99)
            b.barrier_wait(0)
            b.load_check(addr, 99)
            builders.append(b)
        r = run(sc, builders)
        assert r.func_errors == 0

    def test_capacity_evictions(self):
        """March past L1 capacity; evictions notify homes and the protocol
        stays sound."""
        sc = make_config(2)
        b = TraceBuilder()
        n_lines = 128 * 4 + 8
        for i in range(n_lines):
            b.store_value(i * 64, i)
        for i in range(0, n_lines, 7):
            b.load_check(i * 64, i)
        r = run(sc, [b, TraceBuilder()])
        assert r.func_errors == 0
        assert r.mem_counters["evictions"].sum() >= 1


class TestShL2MESI:
    def test_lone_reader_gets_exclusive_silent_upgrade(self):
        """MESI: a lone read grants E; the following write upgrades E→M
        with NO further protocol messages (write hits locally)."""
        sc = make_config(2, "pr_l1_sh_l2_mesi")
        b = TraceBuilder()
        b.load_check(0x40, 0)       # lone read -> EXCLUSIVE
        b.store_value(0x40, 3)      # silent E->M (write hit)
        b.load_check(0x40, 3)
        r = run(sc, [b, TraceBuilder()])
        assert r.func_errors == 0
        mc = r.mem_counters
        assert mc["l1d_read_misses"][0] == 1
        assert mc["l1d_write_hits"][0] == 1    # MSI would write-miss here
        assert mc["invalidations"].sum() == 0

    def test_msi_same_scenario_write_misses(self):
        """The same trace under sh_l2 MSI must upgrade through the home."""
        sc = make_config(2, "pr_l1_sh_l2_msi")
        b = TraceBuilder()
        b.load_check(0x40, 0)
        b.store_value(0x40, 3)
        b.load_check(0x40, 3)
        r = run(sc, [b, TraceBuilder()])
        assert r.func_errors == 0
        assert r.mem_counters["l1d_write_misses"][0] == 1

    def test_second_reader_downgrades_exclusive(self):
        sc = make_config(2, "pr_l1_sh_l2_mesi")
        addr = 0x0
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.load_check(addr, 0)      # E at tile 0
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 0)      # WB downgrades tile 0 E->S
        r = run(sc, [b0, b1])
        assert r.func_errors == 0


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
