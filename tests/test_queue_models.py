"""Queue-model tests: reference-behavior checks + contention sweeps.

Mirrors the reference's queue-model usage: back-to-back packets on one
queue must serialize (`queue_model_basic.cc:36-61`), idle queues add no
delay, and the M/G/1 fallback reproduces the analytical waiting time
(`queue_model_m_g_1.cc:18-47`).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile
from graphite_tpu.models.queue_models import (
    QueueParams, compute_queue_delay, make_queues,
)


def drive(params, arrivals, procs):
    """Drive one queue (lane 0) through a packet sequence; returns delays."""
    q = make_queues(1, params)
    m = jnp.asarray([True])
    out = []
    for t, p in zip(arrivals, procs):
        q, d = compute_queue_delay(
            params, q, jnp.asarray([t], jnp.int64), jnp.asarray([p], jnp.int64), m)
        out.append(int(d[0]))
    return out, q


class TestBasic:
    def test_idle_queue_no_delay(self):
        p = QueueParams(kind="basic", moving_avg_enabled=False)
        delays, _ = drive(p, [100, 300, 600], [10, 10, 10])
        assert delays == [0, 0, 0]

    def test_back_to_back_serializes(self):
        # pkt at t=0 (proc 10) -> queue busy till 10; pkt at t=0 waits 10;
        # pkt at t=5 waits 15 (`queue_time - ref_time`)
        p = QueueParams(kind="basic", moving_avg_enabled=False)
        delays, q = drive(p, [0, 0, 5], [10, 10, 10])
        assert delays == [0, 10, 15]
        assert int(q.total_delay[0]) == 25
        assert int(q.total_utilized[0]) == 30

    def test_vectorized_lanes_independent(self):
        p = QueueParams(kind="basic", moving_avg_enabled=False)
        q = make_queues(2, p)
        t = jnp.asarray([0, 0], jnp.int64)
        pr = jnp.asarray([10, 20], jnp.int64)
        m = jnp.asarray([True, True])
        q, d0 = compute_queue_delay(p, q, t, pr, m)
        q, d1 = compute_queue_delay(p, q, t, pr, m)
        assert d0.tolist() == [0, 0]
        assert d1.tolist() == [10, 20]

    def test_mask_skips_lane(self):
        p = QueueParams(kind="basic", moving_avg_enabled=False)
        q = make_queues(1, p)
        q, d = compute_queue_delay(
            p, q, jnp.asarray([0], jnp.int64), jnp.asarray([10], jnp.int64),
            jnp.asarray([False]))
        assert int(q.queue_time[0]) == 0
        assert int(q.total_requests[0]) == 0


class TestMG1:
    def test_first_packet_free(self):
        p = QueueParams(kind="m_g_1")
        delays, _ = drive(p, [0], [10])
        assert delays == [0]

    def test_matches_reference_formula(self):
        # Constant service time s, arrivals at rate lambda: M/D/1 wait =
        # 0.5 * mu * lam * (1/mu^2) / (mu - lam)
        p = QueueParams(kind="m_g_1")
        s = 10
        arrivals = list(range(0, 2000, 40))  # lam = 1/40, mu = 1/10
        delays, q = drive(p, arrivals, [s] * len(arrivals))
        mu, lam_exp = 1.0 / s, 1.0 / 40
        # after warmup the delay settles near the analytical value
        # (arrival rate estimated from newest_arrival)
        expect = 0.5 * mu * lam_exp * (1 / mu**2) / (mu - lam_exp)
        tail = delays[-5:]
        assert all(abs(d - expect) <= 2 for d in tail), (tail, expect)


class TestHistoryWindowed:
    def test_in_window_matches_basic_tail(self):
        ph = QueueParams(kind="history_tree", max_list_size=100,
                         min_processing_time=10)
        pb = QueueParams(kind="basic", moving_avg_enabled=False)
        seq = [(0, 10), (0, 10), (5, 10), (100, 10), (101, 10)]
        dh, _ = drive(ph, [a for a, _ in seq], [p for _, p in seq])
        db, _ = drive(pb, [a for a, _ in seq], [p for _, p in seq])
        assert dh == db

    def test_old_packet_uses_analytical(self):
        p = QueueParams(kind="history_tree", max_list_size=2,
                        min_processing_time=5)
        # push window far ahead, then send an ancient packet
        arrivals = [1000, 1005, 1010, 1015]
        q = make_queues(1, p)
        m = jnp.asarray([True])
        for t in arrivals:
            q, _ = compute_queue_delay(
                p, q, jnp.asarray([t], jnp.int64), jnp.asarray([5], jnp.int64), m)
        assert int(q.window_start[0]) > 0
        q, d = compute_queue_delay(
            p, q, jnp.asarray([1], jnp.int64), jnp.asarray([5], jnp.int64), m)
        assert int(q.analytical_used[0]) == 1

    def test_config_resolution(self):
        cfg = ConfigFile.from_string("""
[queue_model/history_tree]
max_list_size = 77
analytical_model_enabled = false
""")
        p = QueueParams.from_config(cfg, "history_tree", 13)
        assert p.max_list_size == 77
        assert not p.analytical_enabled
        assert p.history_span == 77 * 13


class TestContentionSweep:
    @pytest.mark.parametrize("load", [0.2, 0.5, 0.8])
    def test_utilization_tracks_offered_load(self, load):
        """Windowed-tail delay grows with offered load and stays near the
        exact sequential free-list computation for in-order arrivals."""
        rng = np.random.default_rng(42)
        s = 10
        gap = s / load
        arrivals = np.cumsum(rng.exponential(gap, 500)).astype(np.int64)
        p = QueueParams(kind="history_tree", min_processing_time=s)
        delays, q = drive(p, arrivals.tolist(), [s] * len(arrivals))
        # exact sequential reference (tail model is exact for sorted input)
        qt, exact = 0, []
        for t in arrivals:
            d = max(0, qt - t)
            exact.append(d)
            qt = max(qt, t) + s
        assert delays == exact


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
