"""Functional timing tests for the dense emesh_hop_by_hop model.

Hand-computed expectations follow the REFERENCE serial semantics
(`network_model_emesh_hop_by_hop.cc:146-265` + router/link delays 1/1):
 - injection router: router_delay + injection-port queue delay;
 - every mesh hop INCLUDING the SELF delivery step: router+link + that
   output port's queue delay (read at arrival, before paying the step);
 - receiver serialization = num_flits, skipped for self-sends.

The dense implementation must reproduce these exactly for cross-call
queueing (occupancy left by earlier calls); same-call multi-packet
interactions follow the documented approximation contract instead.
"""

import jax.numpy as jnp
import numpy as np

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.models.network_hop_by_hop import (
    HopByHopParams, init_noc_state, route_hop_by_hop,
)

CFG = """
[general]
total_cores = 16
max_frequency = 1.0
[network]
user = emesh_hop_by_hop
memory = emesh_hop_by_hop
[network/emesh_hop_by_hop]
flit_width = 64
[network/emesh_hop_by_hop/router]
delay = 1
[network/emesh_hop_by_hop/link]
delay = 1
"""


def make(queue_kind="history_list"):
    sc = SimConfig(ConfigFile.from_string(
        CFG + f"[network/emesh_hop_by_hop/queue_model]\nenabled = true\n"
        f"type = {queue_kind}\n"))
    p = HopByHopParams.from_config(sc, "user")
    return p, init_noc_state(p)


def one(p, nst, src, dst, t_send_ps, bits=64):
    L = 1
    st, arr, zl, cont = route_hop_by_hop(
        p, nst,
        jnp.asarray([src], jnp.int32), jnp.asarray([dst], jnp.int32),
        jnp.asarray([bits], jnp.int64), jnp.asarray([t_send_ps], jnp.int64),
        jnp.ones((L,), bool), jnp.asarray(True))
    return st, int(arr[0]), int(zl[0]), int(cont[0])


def test_single_packet_zero_load():
    """src 0 -> dst 3 on the 4x4 mesh: 3 horizontal hops + SELF.
    cycles = 1 (inject router) + 4*(router+link) + 1 flit ser = 10."""
    p, nst = make()
    assert (p.mesh_width, p.mesh_height) == (4, 4)
    nst, arr, zl, cont = one(p, nst, 0, 3, 0)
    assert (arr, zl, cont) == (10_000, 10_000, 0)


def test_xy_turn_zero_load():
    """src 0 -> dst 15: 3 right + 3 up + SELF = 7 steps.
    cycles = 1 + 7*2 + 1 = 16."""
    p, nst = make()
    nst, arr, zl, cont = one(p, nst, 0, 15, 0)
    assert (arr, zl, cont) == (16_000, 16_000, 0)


def test_self_send():
    """src == dst: inject + SELF step, no receiver serialization:
    cycles = 1 + 2 = 3."""
    p, nst = make()
    nst, arr, zl, cont = one(p, nst, 5, 5, 0)
    assert (arr, zl, cont) == (3_000, 3_000, 0)


def test_cross_call_queueing_matches_serial():
    """A second identical packet sent at the same time on a later call
    queues exactly one cycle behind the first at the injection port and
    then rides in its wake (hand-computed serial result: 11 cycles)."""
    p, nst = make()
    nst, arr1, _, c1 = one(p, nst, 0, 3, 0)
    nst, arr2, zl2, c2 = one(p, nst, 0, 3, 0)
    assert (arr1, c1) == (10_000, 0)
    assert (arr2, zl2, c2) == (11_000, 10_000, 1_000)


def test_later_packet_clears_backlog():
    """A packet sent long after the backlog drained sees zero contention."""
    p, nst = make()
    nst, _, _, _ = one(p, nst, 0, 3, 0)
    nst, arr, _, cont = one(p, nst, 0, 3, 1_000_000)
    assert cont == 0 and arr == 1_010_000


def test_contention_disabled():
    sc = SimConfig(ConfigFile.from_string(
        CFG + "[network/emesh_hop_by_hop/queue_model]\nenabled = false\n"))
    p = HopByHopParams.from_config(sc, "user")
    nst = init_noc_state(p)
    nst, arr1, _, c1 = one(p, nst, 0, 3, 0)
    nst, arr2, _, c2 = one(p, nst, 0, 3, 0)
    assert arr1 == arr2 == 10_000 and c1 == c2 == 0


def test_port_disjoint_paths_independent():
    """Packets on disjoint rows never share ports: no cross contention."""
    p, nst = make()
    nst, _, _, _ = one(p, nst, 0, 3, 0)      # row 0
    nst, arr, _, cont = one(p, nst, 4, 7, 0)  # row 1
    assert cont == 0 and arr == 10_000


class TestGoldenDifferential:
    """Engine (dense-grid) vs golden (serial per-hop oracle): bit-exact
    on serialized traffic (<=1 packet per subquantum iteration) where the
    same-call approximation contract cannot bite."""

    CFG = """
[general]
total_cores = 16
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[network]
user = emesh_hop_by_hop
memory = magic
[network/emesh_hop_by_hop]
flit_width = 64
[network/emesh_hop_by_hop/router]
delay = 1
[network/emesh_hop_by_hop/link]
delay = 1
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""

    def _run_both(self, batch):
        from graphite_tpu.config import ConfigFile, SimConfig
        from graphite_tpu.engine.simulator import Simulator
        from graphite_tpu.golden import run_golden

        sc = SimConfig(ConfigFile.from_string(self.CFG))
        return Simulator(sc, batch).run(), run_golden(sc, batch)

    def _diff(self, batch):
        import numpy as np

        res, gold = self._run_both(batch)
        np.testing.assert_array_equal(res.clock_ps, gold.clock_ps)
        np.testing.assert_array_equal(
            res.recv_instructions, gold.recv_instructions)

    def test_ping_pong_differential(self):
        from graphite_tpu.trace import synthetic

        self._diff(synthetic.ping_pong_trace(16, n_rounds=25))

    def test_token_ring_differential(self):
        """A single token circulating the full ring — long paths, one
        packet in flight globally, repeated port reuse."""
        from graphite_tpu.trace.schema import TraceBatch, TraceBuilder

        bs = [TraceBuilder() for _ in range(16)]
        for lap in range(3):
            for t in range(16):
                if not (lap == 0 and t == 0):
                    bs[t].recv((t - 1) % 16, 16)
                bs[t].bblock(5, 5)
                bs[t].send((t + 1) % 16, 16)
        bs[0].recv(15, 16)
        self._diff(TraceBatch.from_builders(bs))

    def test_mutex_serialized_crossing_traffic(self):
        """Mutex-gated senders from different rows share column ports."""
        from graphite_tpu.trace.schema import TraceBatch, TraceBuilder

        bs = [TraceBuilder() for _ in range(16)]
        bs[0].mutex_init(0)
        bs[0].barrier_init(1, 16)
        for b in bs:
            b.barrier_wait(1)
        for r in range(3):
            for t in range(4):
                s = t * 4          # senders down column 0
                bs[s].mutex_lock(0)
                bs[s].send(15, 32)
                bs[s].mutex_unlock(0)
                bs[15].recv(s, 32)
        self._diff(TraceBatch.from_builders(bs))

    def test_free_running_envelope(self):
        """Free-running uniform-random traffic: every tile sends each
        round, so whole waves of packets resolve against pre-call port
        state (the documented same-call batching contract).  Measured
        divergence vs the serial oracle is ~9% on this adversarial
        pattern (worst case: maximal same-iteration concurrency); the
        test pins a 15% ceiling so contract regressions surface.  Note
        the reference itself is nondeterministic here (its lax schemes
        admit arbitrary cross-thread packet interleavings), and
        serialized traffic — where the reference IS deterministic — is
        bit-exact (tests above)."""
        import numpy as np

        from graphite_tpu.trace import synthetic

        batch = synthetic.message_ring_batch(
            16, n_rounds=30, compute_per_round=7, pattern="uniform_random")
        res, gold = self._run_both(batch)
        # NOTE: recv_instructions cannot be asserted exactly here — it
        # counts only receives that WAITED (arrival > clock), which is
        # itself timing-dependent and shifts with the contention deltas
        rel = np.abs(res.clock_ps.astype(float)
                     - gold.clock_ps.astype(float))
        rel = rel / np.maximum(gold.clock_ps.astype(float), 1.0)
        assert rel.max() <= 0.15, (
            f"hop-by-hop same-call divergence {rel.max():.4f} > 15%")
