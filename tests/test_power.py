"""Power/area model tests: native library build + interface behavior.

Mirrors the reference's McPAT/DSENT roles (SURVEY §2.9): structure area,
leakage, per-event dynamic energy, DVFS voltage scaling (dynamic ~ V^2,
leakage falls with voltage), and the per-tile energy monitor summary.
"""

import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.power import (
    DSENTInterface, McPATCacheInterface, McPATCoreInterface,
    TileEnergyMonitor, load_native,
)
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


class TestNativeLibrary:
    def test_builds_and_loads(self):
        lib = load_native()
        assert lib.energy_model_abi_version() == 1

    def test_cache_scales_with_size(self):
        small = McPATCacheInterface(22, 32 * 1024, 4)
        big = McPATCacheInterface(22, 512 * 1024, 8)
        assert big.area_mm2() > small.area_mm2()
        assert big.at_voltage(1.0).read_energy_j > \
            small.at_voltage(1.0).read_energy_j
        assert big.at_voltage(1.0).leakage_power_w > \
            small.at_voltage(1.0).leakage_power_w

    def test_dynamic_energy_scales_v_squared(self):
        c = McPATCacheInterface(22, 64 * 1024, 4)
        e_hi = c.at_voltage(1.0).read_energy_j
        e_lo = c.at_voltage(0.8).read_energy_j
        assert e_lo == pytest.approx(e_hi * 0.64, rel=1e-6)

    def test_leakage_falls_with_voltage(self):
        core = McPATCoreInterface(22)
        assert core.at_voltage(0.8).leakage_power_w < \
            core.at_voltage(1.0).leakage_power_w

    def test_technology_scaling(self):
        c22 = McPATCacheInterface(22, 64 * 1024, 4)
        c45 = McPATCacheInterface(45, 64 * 1024, 4)
        assert c22.area_mm2() < c45.area_mm2()
        assert c22.at_voltage(1.0).read_energy_j < \
            c45.at_voltage(1.0).read_energy_j

    def test_noc_energy_positive(self):
        d = DSENTInterface(22)
        assert d.router_dynamic_energy_j(1.0, 100) > 0
        assert d.link_dynamic_energy_j(1.0, 100) > 0
        assert d.static_power_w(1.0) > 0


class TestTileEnergyMonitor:
    def _run(self):
        sc = SimConfig(ConfigFile.from_string("""
[general]
total_cores = 2
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = magic
[core/static_instruction_costs]
mov = 1
ialu = 1
[clock_skew_management]
scheme = lax
"""))
        b0 = TraceBuilder()
        for i in range(20):
            b0.store_value(i * 64, i)
        for _ in range(30):
            b0.instr(Op.IALU)
        sim = Simulator(sc, TraceBatch.from_builders([b0, TraceBuilder()]))
        return sim, sim.run()

    def test_energy_breakdown_and_summary(self):
        sim, results = self._run()
        mon = TileEnergyMonitor(sim, results)
        e = mon.tile_energy_j(0)
        assert e["total"] > 0
        assert e["core_dynamic"] > 0
        assert e["l1d_dynamic"] > 0
        assert e["dram_dynamic"] > 0
        # the idle tile burns only leakage
        e1 = mon.tile_energy_j(1)
        assert e1["core_dynamic"] == 0
        s = mon.output_summary()
        assert "Tile Energy Monitor Summary" in s
        assert "Total Energy (in J)" in s

    def test_lower_voltage_lower_dynamic_energy(self):
        sim, results = self._run()
        mon = TileEnergyMonitor(sim, results)
        assert mon.tile_energy_j(0, voltage=0.8)["core_dynamic"] < \
            mon.tile_energy_j(0, voltage=1.0)["core_dynamic"]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_cache_banks_knob():
    """num_banks (`carbon_sim.cfg:212,223,234`) — the reference's only
    consumer is the McPAT cache config: banked arrays pay per-bank
    dynamic energy but ALL banks leak (and occupy area)."""
    from graphite_tpu.power.interface import McPATCacheInterface

    one = McPATCacheInterface(45, 512 * 1024, 8, 64)
    four = McPATCacheInterface(45, 512 * 1024, 8, 64, num_banks=4)
    # per-access dynamic energy shrinks with bank size
    assert four.dynamic_energy_j(1.0, 1000, 0) < one.dynamic_energy_j(
        1.0, 1000, 0)
    # total leakage and area do not (every bank leaks)
    assert four.leakage_energy_j(1.0, 1.0) > 0.5 * one.leakage_energy_j(
        1.0, 1.0)
