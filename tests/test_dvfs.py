"""DVFS manager tests: V/f tables, rc codes, in-trace frequency scaling.

Mirrors the reference unit tests `tests/unit/dvfs_basic`, `dvfs_error_codes`
and `frequency_scaling_simple`: AUTO picks the minimum voltage for a
frequency, HOLD fails above the current voltage's maximum, invalid
tile/domain/frequency return the `dvfs.h` rc codes, and a frequency change
rescales subsequent instruction costs.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.models import dvfs as dv
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=2, max_freq="2.0"):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = {max_freq}
technology_node = 22
[dvfs]
synchronization_delay = 2
[dvfs/domains]
[dvfs]
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY> \
<1.0, NETWORK_USER, NETWORK_MEMORY>"
[network]
user = magic
memory = magic
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax
"""
    return SimConfig(ConfigFile.from_string(text))


def run_sim(sc, builders):
    sim = Simulator(sc, TraceBatch.from_builders(builders))
    return sim, sim.run()


class TestLevels:
    def test_min_voltage_auto(self):
        p = dv.DvfsParams.from_config(make_config().cfg)
        # max_frequency = 2 GHz: 2000 MHz needs 1.0 V; 0.5*2000=1000 runs
        # at factor 0.5 -> 0.84 V; 0.37*2000=740 at 0.8 V
        assert p.min_voltage_mv(2000) == 1000
        assert p.min_voltage_mv(1000) == 840
        assert p.min_voltage_mv(700) == 800
        assert p.min_voltage_mv(2001) == -1

    def test_initial_voltage_matches_domain_freq(self):
        sc = make_config()
        sim = Simulator(sc, TraceBatch.from_builders(
            [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
        man = dv.DVFSManager(sim)
        rc, f, v = man.get_dvfs(0, 0)
        assert rc == dv.RC_OK
        assert f == pytest.approx(1.0)
        assert v == pytest.approx(0.84)  # 1 GHz at factor 0.5 of 2 GHz


class TestErrorCodes:
    def test_reference_rc_codes(self):
        """dvfs_error_codes.cc sequence."""
        sc = make_config()
        sim = Simulator(sc, TraceBatch.from_builders(
            [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
        man = dv.DVFSManager(sim)
        assert man.get_dvfs(-1, 0)[0] == dv.RC_INVALID_TILE
        assert man.get_dvfs(0, 99)[0] == dv.RC_INVALID_DOMAIN
        assert man.set_dvfs(0, 0, 0.0) == dv.RC_INVALID_FREQUENCY
        assert man.set_dvfs(0, 0, 1.0, voltage_flag=5) == \
            dv.RC_INVALID_VOLTAGE_OPTION
        assert man.set_dvfs(0, 0, 100.0) == dv.RC_INVALID_FREQUENCY
        # drop to a low voltage, then HOLD a too-fast frequency
        assert man.set_dvfs(0, 0, 0.1) == dv.RC_OK
        assert man.set_dvfs(0, 0, 2.0, dv.HOLD) == \
            dv.RC_ABOVE_MAX_FOR_VOLTAGE

    def test_basic_set_get(self):
        """dvfs_basic.cc: AUTO then HOLD round trip."""
        sc = make_config()
        sim = Simulator(sc, TraceBatch.from_builders(
            [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
        man = dv.DVFSManager(sim)
        assert man.set_dvfs(0, 0, 2.0) == dv.RC_OK
        rc, f, v = man.get_dvfs(0, 0)
        assert (f, v) == (pytest.approx(2.0), pytest.approx(1.0))
        assert man.set_dvfs(0, 0, 1.0, dv.HOLD) == dv.RC_OK
        rc, f, v = man.get_dvfs(0, 0)
        assert (f, v) == (pytest.approx(1.0), pytest.approx(1.0))  # held


class TestInTraceScaling:
    def test_frequency_change_rescales_costs(self):
        """frequency_scaling_simple analog: 4 ialu at 1 GHz, retune to
        2 GHz, 4 more: 4*1000 + 4*500 ps."""
        b = TraceBuilder()
        for _ in range(4):
            b.instr(Op.IALU)
        b.dvfs_set(0, 2000)
        for _ in range(4):
            b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        assert r.clock_ps[0] == 4000 + 2000
        assert int(np.asarray(sim.state.dvfs.errors).sum()) == 0
        assert int(np.asarray(sim.state.dvfs.voltage_mv)[0, 0]) == 1000

    def test_invalid_in_trace_set_counts_error(self):
        b = TraceBuilder()
        b.instr(Op.IALU)
        b.dvfs_set(0, 5000)        # > 2 GHz max: rejected
        b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        assert r.clock_ps[0] == 2000   # frequency unchanged
        assert int(np.asarray(sim.state.dvfs.errors)[0]) == 1

    def test_hold_in_trace_fails_above_voltage_max(self):
        b = TraceBuilder()
        b.dvfs_set(0, 740)             # AUTO: drops voltage to 0.8 V
        b.dvfs_set(0, 2000, hold=True)  # exceeds 0.8 V max: rejected
        b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        # still at 740 MHz: one ialu = ceil cycle at 740 MHz
        assert int(np.asarray(sim.state.dvfs.errors)[0]) == 1
        assert int(np.asarray(sim.state.dvfs.freq_mhz)[0, 0]) == 740

    def test_non_core_domain_set_tracked(self):
        b = TraceBuilder()
        b.dvfs_set(1, 1500)            # NETWORK domain
        b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        assert r.clock_ps[0] == 1000   # core frequency untouched
        assert int(np.asarray(sim.state.dvfs.freq_mhz)[0, 1]) == 1500


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
