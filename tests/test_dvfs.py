"""DVFS manager tests: V/f tables, rc codes, in-trace frequency scaling.

Mirrors the reference unit tests `tests/unit/dvfs_basic`, `dvfs_error_codes`
and `frequency_scaling_simple`: AUTO picks the minimum voltage for a
frequency, HOLD fails above the current voltage's maximum, invalid
tile/domain/frequency return the `dvfs.h` rc codes, and a frequency change
rescales subsequent instruction costs.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.models import dvfs as dv
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=2, max_freq="2.0"):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = {max_freq}
technology_node = 22
[dvfs]
synchronization_delay = 2
[dvfs/domains]
[dvfs]
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY> \
<1.0, NETWORK_USER, NETWORK_MEMORY>"
[network]
user = magic
memory = magic
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax
"""
    return SimConfig(ConfigFile.from_string(text))


def run_sim(sc, builders):
    sim = Simulator(sc, TraceBatch.from_builders(builders))
    return sim, sim.run()


class TestLevels:
    def test_min_voltage_auto(self):
        p = dv.DvfsParams.from_config(make_config().cfg)
        # max_frequency = 2 GHz: 2000 MHz needs 1.0 V; 0.5*2000=1000 runs
        # at factor 0.5 -> 0.84 V; 0.37*2000=740 at 0.8 V
        assert p.min_voltage_mv(2000) == 1000
        assert p.min_voltage_mv(1000) == 840
        assert p.min_voltage_mv(700) == 800
        assert p.min_voltage_mv(2001) == -1

    def test_initial_voltage_matches_domain_freq(self):
        sc = make_config()
        sim = Simulator(sc, TraceBatch.from_builders(
            [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
        man = dv.DVFSManager(sim)
        rc, f, v = man.get_dvfs(0, 0)
        assert rc == dv.RC_OK
        assert f == pytest.approx(1.0)
        assert v == pytest.approx(0.84)  # 1 GHz at factor 0.5 of 2 GHz


class TestErrorCodes:
    def test_reference_rc_codes(self):
        """dvfs_error_codes.cc sequence."""
        sc = make_config()
        sim = Simulator(sc, TraceBatch.from_builders(
            [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
        man = dv.DVFSManager(sim)
        assert man.get_dvfs(-1, 0)[0] == dv.RC_INVALID_TILE
        assert man.get_dvfs(0, 99)[0] == dv.RC_INVALID_DOMAIN
        assert man.set_dvfs(0, 0, 0.0) == dv.RC_INVALID_FREQUENCY
        assert man.set_dvfs(0, 0, 1.0, voltage_flag=5) == \
            dv.RC_INVALID_VOLTAGE_OPTION
        assert man.set_dvfs(0, 0, 100.0) == dv.RC_INVALID_FREQUENCY
        # drop to a low voltage, then HOLD a too-fast frequency
        assert man.set_dvfs(0, 0, 0.1) == dv.RC_OK
        assert man.set_dvfs(0, 0, 2.0, dv.HOLD) == \
            dv.RC_ABOVE_MAX_FOR_VOLTAGE

    def test_basic_set_get(self):
        """dvfs_basic.cc: AUTO then HOLD round trip."""
        sc = make_config()
        sim = Simulator(sc, TraceBatch.from_builders(
            [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
        man = dv.DVFSManager(sim)
        assert man.set_dvfs(0, 0, 2.0) == dv.RC_OK
        rc, f, v = man.get_dvfs(0, 0)
        assert (f, v) == (pytest.approx(2.0), pytest.approx(1.0))
        assert man.set_dvfs(0, 0, 1.0, dv.HOLD) == dv.RC_OK
        rc, f, v = man.get_dvfs(0, 0)
        assert (f, v) == (pytest.approx(1.0), pytest.approx(1.0))  # held


class TestInTraceScaling:
    def test_frequency_change_rescales_costs(self):
        """frequency_scaling_simple analog: 4 ialu at 1 GHz, retune to
        2 GHz, 4 more: 4*1000 + 4*500 ps."""
        b = TraceBuilder()
        for _ in range(4):
            b.instr(Op.IALU)
        b.dvfs_set(0, 2000)
        for _ in range(4):
            b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        assert r.clock_ps[0] == 4000 + 2000
        assert int(np.asarray(sim.state.dvfs.errors).sum()) == 0
        assert int(np.asarray(sim.state.dvfs.voltage_mv)[0, 0]) == 1000

    def test_invalid_in_trace_set_counts_error(self):
        b = TraceBuilder()
        b.instr(Op.IALU)
        b.dvfs_set(0, 5000)        # > 2 GHz max: rejected
        b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        assert r.clock_ps[0] == 2000   # frequency unchanged
        assert int(np.asarray(sim.state.dvfs.errors)[0]) == 1

    def test_hold_in_trace_fails_above_voltage_max(self):
        b = TraceBuilder()
        b.dvfs_set(0, 740)             # AUTO: drops voltage to 0.8 V
        b.dvfs_set(0, 2000, hold=True)  # exceeds 0.8 V max: rejected
        b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        # still at 740 MHz: one ialu = ceil cycle at 740 MHz
        assert int(np.asarray(sim.state.dvfs.errors)[0]) == 1
        assert int(np.asarray(sim.state.dvfs.freq_mhz)[0, 0]) == 740

    def test_non_core_domain_set_tracked(self):
        b = TraceBuilder()
        b.dvfs_set(1, 1500)            # NETWORK domain
        b.instr(Op.IALU)
        sim, r = run_sim(make_config(), [b, TraceBuilder()])
        assert r.clock_ps[0] == 1000   # core frequency untouched
        assert int(np.asarray(sim.state.dvfs.freq_mhz)[0, 1]) == 1500


class TestLevelTableValidation:
    """`dvfs.levels.validate_levels`: the monotone V-per-f contract."""

    def test_valid_table_passes(self):
        from graphite_tpu.dvfs import validate_levels

        validate_levels((1000, 840, 800), (2000, 1000, 740))

    @pytest.mark.parametrize("volts,freqs,msg", [
        ((1000, 840), (2000,), "length mismatch"),
        ((), (), "empty"),
        ((1000, 0), (2000, 1000), "positive"),
        ((1000, -5), (2000, 1000), "positive"),
        ((1000, 840), (2000, 0), "positive"),
        ((840, 1000), (1000, 2000), "descending"),
        ((1000, 1000), (2000, 1000), "descending"),
        ((1000, 840), (1000, 2000), "monotone"),
    ])
    def test_invalid_tables_raise(self, volts, freqs, msg):
        from graphite_tpu.dvfs import validate_levels

        with pytest.raises(ValueError, match=msg):
            validate_levels(volts, freqs)

    def test_energy_scale_q16_hand_rows(self):
        """V²·f factor vs hand-computed Q16 rows (ref = level 0)."""
        import jax.numpy as jnp

        from graphite_tpu.dvfs import energy_scale_q16

        p = dv.DvfsParams.from_config(make_config().cfg)
        # ref point: 1000 mV, 2000 MHz.  Hand Q16 per stage:
        #   (mv²·256 // ref_mv²) * (f·256 // ref_f)
        sc = energy_scale_q16(
            p, jnp.asarray([2000, 1000, 740]), jnp.asarray(
                [1000, 840, 800]))
        v = np.asarray(sc)
        assert v[0] == 256 * 256                   # table top: exactly 1.0
        assert v[1] == ((840 * 840 * 256) // (1000 * 1000)) \
            * ((1000 * 256) // 2000)               # 180 * 128
        assert v[2] == ((800 * 800 * 256) // (1000 * 1000)) \
            * ((740 * 256) // 2000)                # 163 * 94


def _mem_config(sync_delay, domains):
    from graphite_tpu.tools._template import config_text

    return SimConfig(ConfigFile.from_string(
        config_text(4, shared_mem=True, clock_scheme="lax")
        + f"""
[general]
technology_node = 22
[dvfs]
max_frequency = 1.0
synchronization_delay = {sync_delay}
domains = "{domains}"
"""))


_SPLIT = ("<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE>, "
          "<1.0, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>")
_FLAT = ("<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY, "
         "NETWORK_USER, NETWORK_MEMORY>")


def _mem_trace():
    from graphite_tpu.trace import synthetic

    return synthetic.memory_stress_trace(
        4, n_accesses=10, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=11)


class TestSyncDelayTransitions:
    """Boundary-crossing synchronization delay: charged in BOTH
    directions of an L2<->network handoff (`MemParams.sync_cycles` is
    symmetric in its module pair), live when the domain split is real,
    a Python 0 when it is not."""

    def test_multi_domain_delay_slows_and_knob_matches_config(self):
        batch = _mem_trace()
        r0 = Simulator(_mem_config(0, _SPLIT), batch).run()
        r8 = Simulator(_mem_config(8, _SPLIT), batch).run()
        assert int(r8.completion_time_ps) > int(r0.completion_time_ps)

        # the traced knob reproduces each constant-folded config
        # bit-for-bit — the round-8 "structurally inert" finding is
        # closed only if this holds on a GENUINE multi-domain split
        from graphite_tpu.sweep import SweepRunner

        out = SweepRunner(_mem_config(0, _SPLIT), [batch, batch],
                          [{"sync_delay_cycles": 0},
                           {"sync_delay_cycles": 8}],
                          shard_batch=False).run()
        for res, ref in zip(out.results, (r0, r8)):
            assert np.array_equal(np.asarray(res.clock_ps),
                                  np.asarray(ref.clock_ps))

    def test_single_domain_delay_inert(self):
        batch = _mem_trace()
        r0 = Simulator(_mem_config(0, _FLAT), batch).run()
        r8 = Simulator(_mem_config(8, _FLAT), batch).run()
        assert np.array_equal(np.asarray(r0.clock_ps),
                              np.asarray(r8.clock_ps))


class TestGoldenEquality:
    """Engine vs the hand-stepped golden interpreter with in-trace
    retunes (fixed frequency after the set — the oracle the regress
    rung pins at 16 tiles, here at unit-test size)."""

    def test_fixed_frequency_and_retune_match_golden(self):
        from graphite_tpu.golden.interpreter import run_golden

        sc = make_config()
        b0 = TraceBuilder()
        b0.dvfs_set(0, 2000)
        for _ in range(4):
            b0.instr(Op.IALU)
        b1 = TraceBuilder()
        for _ in range(4):
            b1.instr(Op.IALU)
        b1.dvfs_set(0, 5000)       # rejected: above table max
        b1.dvfs_set(0, 740)
        for _ in range(2):
            b1.instr(Op.IALU)
        batch = TraceBatch.from_builders([b0, b1])
        sim = Simulator(sc, batch)
        r = sim.run()
        g = run_golden(sc, batch)
        assert np.array_equal(np.asarray(r.clock_ps), g.clock_ps)
        assert np.array_equal(np.asarray(r.instruction_count),
                              g.instruction_count)
        assert np.array_equal(np.asarray(sim.state.dvfs.errors),
                              g.dvfs_errors)
        assert g.core_freq_mhz.tolist() == [2000, 740]


class TestEnergyPricing:
    """V²·f-scaled event pricing vs hand-computed rows."""

    def _run(self, prefix_freq=None, dvfs=None):
        from graphite_tpu.obs import EnergyPrices, TelemetrySpec

        b = TraceBuilder()
        if prefix_freq is not None:
            b.dvfs_set(0, prefix_freq)
        for _ in range(8):
            b.instr(Op.IALU)
        tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=16,
                            energy_prices=EnergyPrices(instruction_pj=3))
        sim = Simulator(make_config(), TraceBatch.from_builders(
            [b, TraceBuilder()]), telemetry=tel, dvfs=dvfs)
        r = sim.run()
        return int(r.telemetry.col("energy_pj").sum())

    def test_unscaled_baseline(self):
        assert self._run() == 8 * 3

    def test_scaled_at_table_top_is_identity(self):
        """2000 MHz @ 1000 mV is the prices' reference point: the
        scaled series reproduces the unscaled one exactly."""
        from graphite_tpu.dvfs import DvfsSpec

        assert self._run(prefix_freq=2000, dvfs=DvfsSpec()) == 8 * 3

    def test_scaled_at_half_frequency_hand_row(self):
        """1 GHz @ 840 mV: (8·3 · (840²·256//1000²)·(1000·256//2000))
        >> 16 = (24 · 180·128) >> 16 = 8 pJ."""
        from graphite_tpu.dvfs import DvfsSpec

        assert self._run(dvfs=DvfsSpec()) == (24 * 180 * 128) >> 16

    def test_scale_energy_false_keeps_raw_prices(self):
        from graphite_tpu.dvfs import DvfsSpec

        assert self._run(dvfs=DvfsSpec(scale_energy=False)) == 8 * 3


class TestSweepKnob:
    """`dvfs_domain_mhz` as a traced campaign axis: the B-wide grid is
    bit-equal to sequential runs pinned at each operating point."""

    def test_grid_matches_sequential(self):
        from graphite_tpu.dvfs import DvfsSpec
        from graphite_tpu.sweep import SweepRunner

        sc = make_config()

        def mk():
            b = TraceBuilder()
            for _ in range(6):
                b.instr(Op.IALU)
            return [b, TraceBuilder()]

        grid = ((2000, 2000), (1000, 2000), (740, 740))
        traces = [TraceBatch.from_builders(mk()) for _ in grid]
        sweep = SweepRunner(sc, traces,
                            [{"dvfs_domain_mhz": p} for p in grid],
                            shard_batch=False, dvfs=DvfsSpec())
        out = sweep.run()
        for i, p in enumerate(grid):
            solo = Simulator(sc, traces[i],
                             mailbox_depth=sweep.mailbox_depth)
            solo.attach_dvfs(DvfsSpec(), domain_mhz=p)
            ref = solo.run()
            assert np.array_equal(np.asarray(out.results[i].clock_ps),
                                  np.asarray(ref.clock_ps)), p

    def test_knob_requires_spec(self):
        from graphite_tpu.sweep import SweepRunner

        sc = make_config()
        b = TraceBuilder()
        b.instr(Op.IALU)
        with pytest.raises(ValueError, match="dvfs"):
            SweepRunner(sc, [TraceBatch.from_builders(
                [b, TraceBuilder()])],
                [{"dvfs_domain_mhz": (1000, 1000)}], shard_batch=False)


class TestServeClassKey:
    """`Job.dvfs` joins the admission class key: spec splits, knob
    points co-batch."""

    def test_dvfs_splits_and_points_share(self):
        from graphite_tpu.dvfs import DvfsSpec
        from graphite_tpu.serve import Job
        from graphite_tpu.serve.admission import AdmissionController

        sc = make_config()

        def mk():
            b = TraceBuilder()
            for _ in range(4):
                b.instr(Op.IALU)
            return TraceBatch.from_builders([b, TraceBuilder()])

        ctrl = AdmissionController()
        k_plain = ctrl.class_key(Job("plain", sc, mk()))
        k_dvfs = ctrl.class_key(Job("dv", sc, mk(), dvfs=DvfsSpec()))
        k_dvfs2 = ctrl.class_key(Job(
            "dv2", sc, mk(), dvfs=DvfsSpec(),
            knobs={"dvfs_domain_mhz": (1000, 1000)}))
        assert k_plain != k_dvfs          # spec splits the class
        assert k_dvfs == k_dvfs2          # the knob point does NOT


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
