"""MOSI protocol tests (`pr_l1_pr_l2_dram_directory_mosi/`).

Beyond the MSI scenarios (which must still pass functionally), MOSI's
distinguishing behaviors are asserted:
 - a read of a MODIFIED line leaves the data dirty at the owner (O state):
   NO DRAM write happens (`processWbRepFromL2Cache` M→OWNED);
 - reads of SHARED/OWNED lines are served cache-to-cache from a sharer,
   not from DRAM (`processShReqFromL2Cache` OWNED/SHARED branch);
 - evicting/invalidating an OWNED line flushes the dirty data to DRAM.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=2, **over):
    extra = "\n".join(f"{k} = {v}" for k, v in over.items())
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
{extra}
[caching_protocol]
type = pr_l1_pr_l2_dram_directory_mosi
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def run(sc, builders, **kw):
    batch = TraceBatch.from_builders(builders)
    sim = Simulator(sc, batch, **kw)
    return sim.run()


class TestMOSIProtocol:
    def test_producer_consumer_no_dram_write(self):
        """Write on tile 0, read on tile 1: data moves cache-to-cache; the
        owner keeps the dirty line in O — zero DRAM writes."""
        sc = make_config(2)
        addr = 0x0
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 42)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 42)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0
        mc = res.mem_counters
        assert mc["l1d_read_misses"][1] == 1
        assert mc["dram_writes"].sum() == 0      # MSI would write back
        # owner's copy supplied the data: one dram read at most (cold fill
        # of the original store)
        assert mc["dram_reads"].sum() == 1

    def test_second_reader_served_cache_to_cache(self):
        """After M→O, a third tile's read is served from a sharer with no
        additional DRAM read."""
        sc = make_config(4)
        addr = 0x0
        b0 = TraceBuilder()
        b0.barrier_init(0, 4)
        b0.store_value(addr, 7)
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 7)
        b1.barrier_wait(0)
        b2 = TraceBuilder()
        b2.barrier_wait(0)
        b2.barrier_wait(0)
        b2.load_check(addr, 7)
        b3 = TraceBuilder()
        b3.barrier_wait(0)
        b3.barrier_wait(0)
        res = run(sc, [b0, b1, b2, b3])
        assert res.func_errors == 0
        mc = res.mem_counters
        assert mc["dram_reads"].sum() == 1       # only the cold fill
        assert mc["dram_writes"].sum() == 0

    def test_write_after_read_sharing_invalidates_owner(self):
        """O-state sweep: writer invalidates sharers AND flushes the owner;
        the new value is then visible everywhere."""
        sc = make_config(3)
        addr = 0x40
        b0 = TraceBuilder()
        b0.barrier_init(0, 3)
        b0.store_value(addr, 1)      # tile 0: M
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.load_check(addr, 9)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 1)       # tile 0 M -> O, tile 1 S
        b1.barrier_wait(0)
        b1.barrier_wait(0)
        b2 = TraceBuilder()
        b2.barrier_wait(0)
        b2.barrier_wait(0)
        b2.store_value(addr, 9)      # EX on OWNED: FLUSH owner + INV sharer
        b2.barrier_wait(0)
        res = run(sc, [b0, b1, b2])
        assert res.func_errors == 0
        assert res.mem_counters["invalidations"].sum() >= 1
        # everything after the cold fill moves cache-to-cache
        assert res.mem_counters["dram_reads"].sum() == 1

    def test_ping_pong_alternating_writers(self):
        sc = make_config(2)
        addr = 0x40
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 1)
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.load_check(addr, 2)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.store_value(addr, 2)
        b1.barrier_wait(0)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0

    def test_owned_upgrade_by_sharer(self):
        """Both read (owner in O, reader in S), then the READER writes:
        upgrade path must flush the owner's dirty line."""
        sc = make_config(2)
        addr = 0x0
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 5)      # tile 0: M
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.load_check(addr, 6)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 5)       # tile 0 -> O, tile 1 -> S
        b1.store_value(addr, 6)      # tile 1 upgrades: owner flushed
        b1.barrier_wait(0)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0

    def test_capacity_evictions_flush_owned(self):
        """March a second tile's reads over the owner's dirty lines, then
        evict: O lines must flush (DRAM writes happen at eviction time)."""
        sc = make_config(2)
        n_lines = 64
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        for i in range(n_lines):
            b0.store_value(i * 64, i)        # tile 0 owns n dirty lines
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        for i in range(n_lines):
            b1.load_check(i * 64, i)         # all M -> O
        # now overflow tile 1's L1/L2 with fresh lines: evictions of S
        # copies; tile 0 still holds O lines
        for i in range(n_lines):
            b1.store_value(0x100000 + i * 64, i)
        b1.barrier_wait(0)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0

    def test_single_tile_msi_equivalence(self):
        """With one tile and no sharing, MOSI timing matches MSI exactly."""
        addr = 0x80
        trace = TraceBuilder()
        trace.store_value(addr, 3)
        trace.load_check(addr, 3)
        b_mosi = run(make_config(1), [trace])
        # the same knobs with the MSI protocol
        sc_msi = SimConfig(ConfigFile.from_string("""
[general]
total_cores = 1
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[caching_protocol]
type = pr_l1_pr_l2_dram_directory_msi
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""))
        trace2 = TraceBuilder()
        trace2.store_value(addr, 3)
        trace2.load_check(addr, 3)
        b_msi = run(sc_msi, [trace2])
        assert b_mosi.clock_ps[0] == b_msi.clock_ps[0]
        assert b_mosi.func_errors == 0


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
