"""RoundRobinThreadScheduler unit tests (`thread_scheduler.cc`,
`round_robin_thread_scheduler.cc`): placement, run queues, yield rotation,
migration, affinity-driven migration."""

import pytest

from graphite_tpu.system.thread_scheduler import RoundRobinThreadScheduler


def test_round_robin_placement_prefers_idle():
    s = RoundRobinThreadScheduler(4)
    tiles = [s.schedule(t) for t in range(4)]
    assert tiles == [0, 1, 2, 3]
    # all busy: least-loaded (first) gets the 5th
    assert s.schedule(4) == 0
    assert s.running_on(0) == 0
    assert list(s.queues[0]) == [0, 4]


def test_exit_promotes_next():
    s = RoundRobinThreadScheduler(2)
    for t in range(4):
        s.schedule(t)
    assert s.running_on(0) == 0
    assert s.thread_exit(0) == 2
    assert s.running_on(0) == 2
    assert s.thread_exit(2) is None


def test_yield_rotates_head_to_tail():
    s = RoundRobinThreadScheduler(1)
    for t in range(3):
        s.schedule(t)
    assert s.running_on(0) == 0
    assert s.yield_thread(0) == 1
    assert list(s.queues[0]) == [1, 2, 0]
    # alone after others exit: yield is a no-op
    s.thread_exit(1)
    s.thread_exit(2)
    assert s.yield_thread(0) == 0


def test_migrate_moves_and_promotes():
    s = RoundRobinThreadScheduler(2)
    for t in range(3):
        s.schedule(t)          # 0->t0, 1->t1, 2->t0 queued
    nxt = s.migrate(0, 1)
    assert nxt == 2            # tile 0's queue head now thread 2
    assert list(s.queues[1]) == [1, 0]
    assert s.threads[0].state == "queued"


def test_affinity_restricts_and_migrates():
    s = RoundRobinThreadScheduler(4)
    s.schedule(0)              # tile 0
    s.set_affinity(0, {2, 3})
    assert s.threads[0].tile in (2, 3)
    assert s.get_affinity(0) == frozenset({2, 3})
    with pytest.raises(ValueError):
        s.migrate(0, 1)
    # placement respects the mask
    s.schedule(1, affinity={3})
    assert s.threads[1].tile == 3


def test_empty_affinity_rejected():
    s = RoundRobinThreadScheduler(2)
    with pytest.raises(ValueError):
        s.schedule(0, affinity=set())
