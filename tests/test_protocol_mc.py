"""Bounded model checker + write-race lane analysis
(analysis/protocol.py, tools/mc.py, the rules.write_race lint).

Five layers under test: the EXHAUSTIVE exploration itself (the pinned
reached-state census — states_explored, transitions, per-protocol
state histograms — so a coverage regression is loud, with the MOSI
O-state and shl2-MESI E-state corners asserted explicitly), the
invariant checkers (the seeded mutant MUST produce a named data-value
counterexample rendered through the round-6 phase names), the
differential replay (every explored transition bit-equal through the
vectorized engines), the write-race lane lint (every scatter in the
registered programs classifies single-writer or commutative; a
synthetic racy lane/matrix trips the error gate), and the `tools/mc.py`
CLI (clean default run exits 0; `--mutant` exits 1 naming the
invariant).

The golden census values are the point, not incidental: if a protocol
change legitimately shrinks or grows the reachable space, update them
HERE with the change that did it.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from graphite_tpu.analysis import protocol as P
from graphite_tpu.analysis import rules
from graphite_tpu.memory.engine import PHASE_NAMES
from graphite_tpu.memory.engine_shl2 import SHL2_PHASE_NAMES


# ---------------------------------------------------------------------------
# exhaustive exploration: the reached-state census
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def msi_2t_1l():
    return P.explore("msi", 2, 1)


@pytest.fixture(scope="module")
def mosi_2t_1l():
    return P.explore("mosi", 2, 1)


@pytest.fixture(scope="module")
def shl2_2t_1l():
    return P.explore("shl2_mesi", 2, 1)


class TestCensus:
    def test_msi_2t_1l(self, msi_2t_1l):
        r = msi_2t_1l
        assert r.ok, [v.render() for v in r.violations]
        assert r.states_explored == 6
        assert r.transitions == 24
        assert r.histogram == {"dir:M": 2, "dir:Sh": 3, "l1d:M": 2,
                               "l1d:S": 3, "l2:M": 2, "l2:S": 3}

    def test_mosi_2t_1l_covers_o_state(self, mosi_2t_1l):
        """The MOSI corner enumeration surfaces: the OWNED state must
        be reached in the directory AND both cache levels (a write
        followed by another tile's read leaves the writer the owner)."""
        r = mosi_2t_1l
        assert r.ok, [v.render() for v in r.violations]
        assert r.states_explored == 8
        assert r.transitions == 32
        assert r.histogram["dir:O"] == 2
        assert r.histogram["l1d:O"] == 2
        assert r.histogram["l2:O"] == 2

    def test_shl2_2t_1l_covers_e_state(self, shl2_2t_1l):
        """The shl2-MESI corner: EXCLUSIVE must be reached (first read
        of an uncached line), including the silent E->M promotion the
        directory only learns about later (dir:E with the holder's L1
        already M is a legal reachable configuration)."""
        r = shl2_2t_1l
        assert r.ok, [v.render() for v in r.violations]
        assert r.states_explored == 11
        assert r.transitions == 44
        assert r.histogram["dir:E"] == 4
        assert r.histogram["l1d:E"] == 2

    def test_fan_in_bounds_2t_1l(self, msi_2t_1l, mosi_2t_1l,
                                 shl2_2t_1l):
        """The [T, k] compaction input: at T=2 every mailbox matrix has
        reachable fan-in 1 and at most one forwarded sharer is ever in
        flight on top of the request itself."""
        for r in (msi_2t_1l, mosi_2t_1l):
            assert r.fan_in == {"req": 1, "fwd": 1, "ack": 1,
                                "evict": 1}
            assert r.max_in_flight == 2
        assert shl2_2t_1l.fan_in == {"req": 1, "fwd": 1, "ack": 1,
                                     "evict": 0}
        assert shl2_2t_1l.max_in_flight == 2

    @pytest.mark.parametrize("protocol,tiles,lines,states,transitions", [
        ("msi", 2, 2, 39, 312),
        ("mosi", 2, 2, 67, 536),
        ("shl2_mesi", 2, 2, 21, 168),
        ("mosi", 3, 1, 20, 120),
        ("msi", 4, 1, 20, 160),
    ])
    def test_bigger_geometries_exhaust_clean(self, protocol, tiles,
                                             lines, states,
                                             transitions):
        r = P.explore(protocol, tiles, lines)
        assert r.ok, [v.render() for v in r.violations]
        assert r.states_explored == states
        assert r.transitions == transitions

    def test_unknown_protocol_raises(self):
        with pytest.raises(ValueError, match="unknown protocol"):
            P.explore("mesif", 2, 1)


# ---------------------------------------------------------------------------
# phase-name rendering: counterexamples speak round-6 phases
# ---------------------------------------------------------------------------


class TestPhaseRendering:
    def test_event_phase_maps_cover_engine_phases(self):
        """Every event kind renders through a REAL engine phase name —
        the maps index into PHASE_NAMES/SHL2_PHASE_NAMES, so a phase
        reorder in the engines breaks this loudly."""
        assert set(P._PRIV_PHASE.values()) <= set(range(len(PHASE_NAMES)))
        assert set(P._SHL2_PHASE.values()) \
            <= set(range(len(SHL2_PHASE_NAMES)))
        assert P.render_event("msi", "req",
                              {"home": 0, "requester": 1,
                               "line": 256, "mtype": "SH",
                               "dstate": 0}).startswith("home_start:")
        assert P.render_event(
            "shl2_mesi", "fill",
            {"tile": 1, "line": 256, "write": True,
             "state": 3}).startswith("requester_fill:")


# ---------------------------------------------------------------------------
# the seeded mutant: the checker's own self-test
# ---------------------------------------------------------------------------


class TestMutant:
    def test_mutant_names_data_value_violation(self):
        r = P.explore("mosi", 2, 1, mutant="mosi-owner-skips-wb")
        assert not r.ok
        v = r.violations[0]
        assert v.invariant == "data-value"
        text = v.render()
        assert "invariant violated: data-value" in text
        # the counterexample is rendered through round-6 phase names
        for phase in ("home_start", "sharer", "home_finish",
                      "requester_fill"):
            assert phase + ":" in text
        # and carries the access path from reset
        assert "path from reset" in text and "W line" in text

    def test_mutant_rejected_for_shl2(self):
        with pytest.raises(ValueError):
            P.explore("shl2_mesi", 2, 1, mutant="mosi-owner-skips-wb")

    def test_unknown_mutant_rejected(self):
        with pytest.raises(ValueError):
            P.explore("mosi", 2, 1, mutant="no-such-mutant")


# ---------------------------------------------------------------------------
# differential replay: the shipped kernels, not just the oracle
# ---------------------------------------------------------------------------


class TestDifferential:
    def test_msi_every_transition_bit_equal(self, msi_2t_1l):
        d = P.differential(msi_2t_1l)
        assert d.n_transitions == 24
        assert d.n_ok == 24
        assert d.ok, d.mismatches[:3]

    def test_shl2_every_transition_bit_equal(self, shl2_2t_1l):
        d = P.differential(shl2_2t_1l)
        assert d.n_transitions == 44
        assert d.ok, d.mismatches[:3]


# ---------------------------------------------------------------------------
# write-race lane lint
# ---------------------------------------------------------------------------


T = 4


def _lane_closed():
    """A racy [T] lane: replace-scatter whose rows come from an opaque
    argument — no writer proof can hold."""
    return jax.make_jaxpr(
        lambda m, i, v: m.at[i].set(v))(
        jnp.zeros((T,), jnp.uint8), jnp.zeros((3,), jnp.int32),
        jnp.zeros((3,), jnp.uint8))


def _matrix_closed():
    return jax.make_jaxpr(
        lambda m, i, v: m.at[i].set(v))(
        jnp.zeros((T, T), jnp.uint8), jnp.zeros((3,), jnp.int32),
        jnp.zeros((3, T), jnp.uint8))


class TestWriteRaceLint:
    def test_gated_msi_classifies_clean(self):
        """Acceptance: every scatter in the registered engine program
        classifies single-writer or commutative — and the req lanes
        specifically are ALL single-writer."""
        from graphite_tpu.analysis.audit import default_programs
        spec = default_programs(T, 64, names=("gated-msi",))[0]
        writes = rules.lane_writes(spec.closed, spec.n_tiles)
        assert writes, "no scatters found — the walk is broken"
        assert all(w.classification != rules.CLASS_ORDERED
                   for w in writes)
        # the round-12 request lanes proper are the uint8 [T] scatters
        # (the int64 lane-shaped writes include the commutative event
        # heap); every one must carry a writer PROOF, not just a
        # commutative combiner
        req = [w for w in writes if w.kind == rules.LANE_REQ
               and w.dtype == "uint8"]
        assert req and all(
            w.classification == rules.CLASS_SINGLE for w in req)
        mat = [w for w in writes if w.kind == rules.LANE_MATRIX]
        assert mat, "no mailbox-matrix scatters found"
        assert rules.write_race(spec.closed, spec.n_tiles) == []
        table = rules.lane_summary(writes)
        assert set(table) <= {rules.LANE_REQ, rules.LANE_MATRIX,
                              rules.LANE_STATE}

    def test_racy_req_lane_trips_gate(self):
        fs = rules.write_race(_lane_closed(), T)
        assert len(fs) == 1
        f = fs[0]
        assert f.severity == rules.SEV_ERROR
        assert f.rule == "write-race"
        assert "req-lane" in f.message
        assert f.data["classification"] == rules.CLASS_ORDERED

    def test_racy_matrix_trips_gate_with_fan_in(self):
        fan = {"req": 1, "fwd": 1, "ack": 1, "evict": 1}
        fs = rules.write_race(_matrix_closed(), T, fan_in=fan)
        assert len(fs) == 1
        assert fs[0].severity == rules.SEV_ERROR
        assert "mailbox-matrix" in fs[0].message
        assert fs[0].data["fan_in"] == fan

    def test_single_writer_lane_passes(self):
        """An iota-indexed lane write (each tile writes its own lane —
        the round-12 shape) must prove single-writer and pass."""
        def fn(m, v):
            return m.at[jnp.arange(T)].set(v)
        closed = jax.make_jaxpr(fn)(jnp.zeros((T,), jnp.uint8),
                                    jnp.zeros((T,), jnp.uint8))
        assert rules.write_race(closed, T) == []
        (w,) = rules.lane_writes(closed, T)
        assert w.kind == rules.LANE_REQ
        assert w.classification == rules.CLASS_SINGLE

    def test_commutative_matrix_passes_as_commutative(self):
        def fn(m, i, v):
            return m.at[i].add(v)
        closed = jax.make_jaxpr(fn)(jnp.zeros((T, T), jnp.int64),
                                    jnp.zeros((3,), jnp.int32),
                                    jnp.zeros((3, T), jnp.int64))
        assert rules.write_race(closed, T) == []
        (w,) = rules.lane_writes(closed, T)
        assert w.classification == rules.CLASS_COMMUTATIVE


# ---------------------------------------------------------------------------
# the CLI
# ---------------------------------------------------------------------------


class TestCLI:
    def test_default_exploration_exits_zero(self, capsys):
        from graphite_tpu.tools.mc import main
        assert main(["--no-differential"]) == 0
        rows = [json.loads(ln) for ln in
                capsys.readouterr().out.splitlines() if ln]
        mc = [r for r in rows if r.get("mc")]
        assert {r["protocol"] for r in mc} \
            == {"msi", "mosi", "shl2_mesi"}
        assert all(r["ok"] and r["violations"] == 0 for r in mc)
        overall = next(r for r in rows if r.get("overall"))
        assert overall["ok"]

    def test_mutant_exits_nonzero_naming_invariant(self, capsys):
        from graphite_tpu.tools.mc import main
        assert main(["--mutant", "--no-differential"]) == 1
        out = capsys.readouterr()
        rows = [json.loads(ln) for ln in out.out.splitlines() if ln]
        vio = [r for r in rows if r.get("violation")]
        assert vio and vio[0]["invariant"] == "data-value"
        assert "home_start:" in vio[0]["counterexample"]
        overall = next(r for r in rows if r.get("overall"))
        assert not overall["ok"]
        assert overall["mutant"] == "mosi-owner-skips-wb"

    def test_unknown_protocol_and_mutant_error(self):
        from graphite_tpu.tools.mc import main
        with pytest.raises(SystemExit):
            main(["--protocols", "mesif"])
        with pytest.raises(SystemExit):
            main(["--mutant", "bogus"])
