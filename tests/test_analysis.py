"""Program auditor (round 8): jaxpr invariant lints + trace validation.

Each lint gets a known-bad fixture — a toy program that violates
exactly the property the rule guards (a fat array riding a cond, a
knob the step ignores, a clock downcast to int32, a gate vmapped into
a select, a debug print in the device loop) — proving the rule FIRES,
plus clean fixtures proving it doesn't cry wolf.  The real default
configs (both memory engines + the sweep program) must then pass the
whole rule set, and the engine-level taint test proves time-dtype
threads through the REAL program, not just toys.

Trace validation: malformed campaign traces (unmatched RECV, bad
opcode, short-counted barrier) must fail `sweep/pack.py` fast with a
named TraceValidationError, and every legitimate workload must pass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from graphite_tpu.analysis import (
    audit, aval_bytes, default_programs, invar_path_strings, iter_eqns,
    used_invar_mask,
)
from graphite_tpu.analysis import rules
from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder
from graphite_tpu.trace.validate import (
    TraceValidationError, validate_batch,
)


# ---- walker ---------------------------------------------------------------


def test_walker_reaches_nested_subjaxprs():
    """cond inside scan inside jit: one traversal sees every level."""

    def inner(c, x):
        return lax.cond(x > 0, lambda v: v + 1.0, lambda v: v - 1.0,
                        c), None

    def f(c, xs):
        return jax.jit(lambda c, xs: lax.scan(inner, c, xs))(c, xs)

    closed = jax.make_jaxpr(f)(0.0, jnp.arange(3.0))
    names = {e.primitive.name for e in iter_eqns(closed)}
    assert {"pjit", "scan", "cond"} <= names


def test_used_invar_mask_sees_through_while():
    def f(a, b, unused):
        def body(carry):
            x, k = carry
            return (x + b, k + 1)

        x, _ = lax.while_loop(lambda c: c[1] < 3, body, (a, 0))
        return x

    closed = jax.make_jaxpr(f)(1.0, 2.0, 3.0)
    assert used_invar_mask(closed) == [True, True, False]


def test_aval_bytes():
    closed = jax.make_jaxpr(lambda x: x + 1)(
        jnp.zeros((8, 4), jnp.int64))
    assert aval_bytes(closed.jaxpr.invars[0].aval) == 8 * 4 * 8


# ---- rule 1: cond-payload -------------------------------------------------


def _fat_cond_jaxpr():
    def f(x):
        return lax.cond(x.sum() > 0, lambda v: v * 2, lambda v: v, x)

    return jax.make_jaxpr(f)(jnp.zeros((64, 64), jnp.float32))


def test_cond_payload_fires_on_fat_cond():
    fs = rules.cond_payload(_fat_cond_jaxpr(), max_bytes=1024)
    assert fs and fs[0].rule == "cond-payload"
    assert fs[0].severity == rules.SEV_ERROR
    assert fs[0].data["bytes"] == 64 * 64 * 4


def test_cond_payload_fires_on_forbidden_signature():
    """The round-6 form: a cond output matching the directory-store
    aval is an error at ANY size (batch axes ignored, so the vmapped
    program is covered too)."""
    fs = rules.cond_payload(_fat_cond_jaxpr(),
                            forbidden=(((64, 64), "float32"),))
    assert fs and "forbidden" in fs[0].message

    def batched(p, x):
        return lax.cond(p, lambda v: v * 2, lambda v: v, x)

    cb = jax.make_jaxpr(jax.vmap(batched, in_axes=(None, 0)))(
        True, jnp.zeros((3, 64, 64), jnp.float32))
    # vmap of an unbatched pred keeps the cond; its output is [3,64,64]
    fs = rules.cond_payload(cb, forbidden=(((64, 64), "float32"),))
    assert fs, "batch-axis-prefixed store escaped the signature match"


def test_cond_payload_clean_on_small_cond():
    def f(x):
        return lax.cond(x > 0, lambda v: v + 1, lambda v: v, x)

    closed = jax.make_jaxpr(f)(1.0)
    assert not rules.cond_payload(closed, max_bytes=1024)


# ---- rule 2: knob-fold ----------------------------------------------------


def _toy_knobs():
    from graphite_tpu.sweep.knobs import KNOB_FIELDS, Knobs

    return Knobs(**{f: jnp.asarray(5, jnp.int64) for f in KNOB_FIELDS})


def _knob_invars(args):
    from graphite_tpu.sweep.knobs import KNOB_FIELDS

    paths = invar_path_strings(args)
    return {f: [i for i, p in enumerate(paths) if p.endswith("." + f)]
            for f in KNOB_FIELDS}, paths


def test_knob_fold_fires_when_step_ignores_knob():
    kn = _toy_knobs()

    def bad_step(x, kn):
        # reads ONE knob, constant-folds the rest (the bug: engine read
        # static params instead of the traced leaves)
        return x + kn.dram_latency_ns + 100

    closed = jax.make_jaxpr(bad_step)(jnp.zeros((), jnp.int64), kn)
    knob_invars, paths = _knob_invars((jnp.zeros((), jnp.int64), kn))
    fs = rules.knob_fold(closed, knob_invars, paths)
    folded = {f.data["knob"] for f in fs}
    assert "dram_latency_ns" not in folded
    assert "hop_latency_cycles" in folded and "quantum_ps" in folded
    assert all(f.severity == rules.SEV_ERROR for f in fs)


def test_knob_fold_clean_when_all_consumed():
    kn = _toy_knobs()

    def good_step(x, kn):
        # every knob enters the arithmetic — incl. one only via a
        # while-loop body (the engines' actual shape)
        def body(c):
            return (c[0] + kn.dram_latency_ns + kn.dram_processing_ns
                    + kn.dir_access_cycles + kn.hop_latency_cycles
                    + kn.sync_delay_cycles, c[1] + 1)

        out, _ = lax.while_loop(lambda c: c[1] < kn.quantum_ps,
                                body, (x, jnp.asarray(0, jnp.int64)))
        return out

    closed = jax.make_jaxpr(good_step)(jnp.zeros((), jnp.int64), kn)
    knob_invars, paths = _knob_invars((jnp.zeros((), jnp.int64), kn))
    assert not rules.knob_fold(closed, knob_invars, paths)


# ---- rule 3: time-dtype ---------------------------------------------------


def test_time_dtype_fires_on_clock_downcast():
    def bad(clock_ps):
        return (clock_ps + 5).astype(jnp.int32)

    closed = jax.make_jaxpr(bad)(jnp.zeros(4, jnp.int64))
    fs = rules.time_dtype(closed, [0])
    assert fs and fs[0].rule == "time-dtype"
    assert fs[0].data == {"from": "int64", "to": "int32"}


def test_time_dtype_fires_through_loop_carry():
    """The realistic shape: the clock advances inside a while loop,
    then an accumulation narrows it."""

    def bad(clock_ps):
        def body(c):
            return (c[0] + 1000, c[1] + 1)

        clk, _ = lax.while_loop(lambda c: c[1] < 8, body,
                                (clock_ps, jnp.asarray(0, jnp.int64)))
        return clk.astype(jnp.int32).sum()

    closed = jax.make_jaxpr(bad)(jnp.zeros(4, jnp.int64))
    assert rules.time_dtype(closed, [0])


def test_time_dtype_fires_in_while_cond_jaxpr():
    """A narrowing inside the loop CONDITION, tainted only via the
    carry fixpoint, must be reported too — the cond jaxpr has no
    feedback edges of its own but sees the stabilized carry marks."""

    def bad(clock_ps):
        def cond(c):
            clk, b, k = c
            return (b.astype(jnp.int32) < 100).all() & (k < 3)

        def body(c):
            clk, b, k = c
            return (clk + 1, clk, k + 1)  # copies clock into carry b

        clk, _, _ = lax.while_loop(
            cond, body, (clock_ps, jnp.zeros_like(clock_ps), 0))
        return clk

    closed = jax.make_jaxpr(bad)(jnp.zeros(4, jnp.int64))
    assert rules.time_dtype(closed, [0])


def test_time_dtype_allows_delta_narrowing():
    """A difference of clocks is a DELTA (time_types.DELTA_DTYPE) —
    int32 is the documented discipline, not a violation."""

    def ok(clock_ps):
        lat = clock_ps - jnp.min(clock_ps)
        return lat.astype(jnp.int32)

    closed = jax.make_jaxpr(ok)(jnp.zeros(4, jnp.int64))
    assert not rules.time_dtype(closed, [0])


def test_time_dtype_allows_untainted_narrowing():
    def ok(clock_ps, count):
        return clock_ps + count.astype(jnp.int32).astype(jnp.int64)

    closed = jax.make_jaxpr(ok)(jnp.zeros(4, jnp.int64),
                                jnp.zeros(4, jnp.int64))
    assert not rules.time_dtype(closed, [0])


def test_time_dtype_threads_through_real_engine():
    """Taint from state.core.clock_ps must survive the REAL program:
    narrowing the final clock after run_simulation fires the rule
    (proving the engine-sized taint pass isn't vacuously clean)."""
    from graphite_tpu.analysis.audit import clock_invar_indices
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.engine.step import run_simulation

    tiles = 4
    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier")))
    batch = synthetic.memory_stress_trace(
        tiles, n_accesses=8, working_set_bytes=1 << 10,
        write_fraction=0.4, shared_fraction=0.5, seed=3)
    sim = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0)
    params, qps = sim.params, sim.quantum_ps

    def bad(st, tr):
        out_st, nq, dl, it = run_simulation(params, tr, st, qps, 256)
        return out_st.core.clock_ps.astype(jnp.int32)  # the violation

    closed = jax.make_jaxpr(bad)(sim.state, sim.device_trace)
    paths = invar_path_strings((sim.state, sim.device_trace))
    fs = rules.time_dtype(closed, clock_invar_indices(paths))
    assert fs, "clock taint failed to thread through the engine program"


# ---- rule 4: vmap-gate ----------------------------------------------------


def test_vmap_gate_fires_on_batched_gate():
    T = 4

    def gated(pred, m):
        return lax.cond(pred, lambda v: v + 1, lambda v: v, m)

    closed = jax.make_jaxpr(jax.vmap(gated))(
        jnp.ones(3, bool), jnp.zeros((3, T, T), jnp.uint8))
    fs = rules.vmap_gate(closed, T, expect_gated=True, n_phases=1)
    assert fs and fs[0].severity == rules.SEV_WARNING
    assert fs[0].data["phase_conds"] == 0


def test_vmap_gate_clean_on_real_cond_or_ungated():
    T = 4

    def gated(pred, m):
        return lax.cond(pred, lambda v: v + 1, lambda v: v, m)

    closed = jax.make_jaxpr(gated)(True, jnp.zeros((T, T), jnp.uint8))
    assert not rules.vmap_gate(closed, T, expect_gated=True, n_phases=1)
    # ungated programs never warn, batched or not
    batched = jax.make_jaxpr(jax.vmap(gated))(
        jnp.ones(3, bool), jnp.zeros((3, T, T), jnp.uint8))
    assert not rules.vmap_gate(batched, T, expect_gated=False,
                               n_phases=1)


def test_vmap_gate_fires_on_gated_sweep_runner():
    """End-to-end: forcing phase_gate=True through a vmapped
    SweepRunner produces a program the rule flags (the PERF round-7
    finding the runner's default avoids)."""
    from graphite_tpu.analysis.audit import spec_from_sweep
    from graphite_tpu.sweep import SweepRunner

    tiles = 4
    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier")))
    traces = [synthetic.memory_stress_trace(
        tiles, n_accesses=8, working_set_bytes=1 << 10,
        write_fraction=0.4, shared_fraction=0.5, seed=s)
        for s in (1, 2)]
    runner = SweepRunner(sc, traces, shard_batch=False,
                         phase_gate=True, mem_gate_bytes=0)
    spec = spec_from_sweep("gated-vmap", runner, max_quanta=256)
    assert spec.expect_gated
    fs = rules.vmap_gate(spec.closed, spec.n_tiles, spec.expect_gated,
                         n_phases=spec.n_phases)
    assert fs and fs[0].rule == "vmap-gate"
    # lowering is abstract: auditing must not materialize the [B, ...]
    # campaign state run() caches for execution
    assert runner._states0 is None


# ---- rule 5: host-sync ----------------------------------------------------


def test_host_sync_fires_on_debug_print():
    def bad(x):
        jax.debug.print("x = {}", x)
        return x + 1

    fs = rules.host_sync(jax.make_jaxpr(bad)(1.0))
    assert fs and fs[0].rule == "host-sync"


def test_host_sync_fires_on_pure_callback():
    def bad(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct((), x.dtype),
            x)

    fs = rules.host_sync(jax.make_jaxpr(bad)(jnp.asarray(1.0)))
    assert fs


def test_host_sync_clean_on_plain_program():
    assert not rules.host_sync(jax.make_jaxpr(lambda x: x * 2)(1.0))


# ---- rule 6: scatter-determinism ------------------------------------------


def test_scatter_determinism_fires_on_aliasing_replace_scatter():
    """Known-bad fixture: a vmapped replace-combiner scatter whose
    traced index rows can collide — XLA leaves the winner
    implementation-defined, so the round-9 masked-add-scatter contract
    must flag it inside batched programs."""
    def bad(x, idx):
        return x.at[idx].set(1.0)

    cb = jax.make_jaxpr(jax.vmap(bad))(
        jnp.zeros((3, 16)), jnp.zeros((3, 4), jnp.int32))
    fs = rules.scatter_determinism(cb, batched=True)
    assert len(fs) == 1 and fs[0].rule == "scatter-determinism"
    assert fs[0].severity == rules.SEV_WARNING
    assert "implementation-defined" in fs[0].message
    # solo (non-batched) programs only police shard_map interiors:
    # the same scatter at top level is out of scope
    assert not rules.scatter_determinism(cb, batched=False)


def test_scatter_determinism_clean_on_commutative_and_unique():
    """Add-combiner scatters commute; unique_indices is an explicit
    no-alias declaration — neither can be nondeterministic."""
    def add(x, idx):
        return x.at[idx].add(1.0)

    ca = jax.make_jaxpr(jax.vmap(add))(
        jnp.zeros((3, 16)), jnp.zeros((3, 4), jnp.int32))
    assert not rules.scatter_determinism(ca, batched=True)

    def uni(x, idx, v):
        return x.at[idx].set(v, unique_indices=True)

    cu = jax.make_jaxpr(jax.vmap(uni))(
        jnp.zeros((3, 16)), jnp.zeros((3, 4), jnp.int32),
        jnp.zeros((3, 4)))
    assert not rules.scatter_determinism(cu, batched=True)


def test_scatter_determinism_proves_iota_and_wraparound_indices():
    """Index provenance: an iota row and the engines' wraparound idiom
    (`where(h < T, h, h - T)` — both arms congruent mod T) are
    collision-free by construction, even though the scatter replaces."""
    def iota(x, v):
        return x.at[jnp.arange(4, dtype=jnp.int32)].set(v)

    ci = jax.make_jaxpr(jax.vmap(iota))(
        jnp.zeros((3, 16)), jnp.zeros((3, 4)))
    assert not rules.scatter_determinism(ci, batched=True)

    def wrap(x, h):
        idx = jnp.where(h < 8, h, h - 8) \
            + jnp.arange(8, dtype=jnp.int32)
        idx = jnp.where(idx < 8, idx, idx - 8)
        return x.at[idx].set(1.0, mode="drop")

    cw = jax.make_jaxpr(jax.vmap(wrap, in_axes=(0, None)))(
        jnp.zeros((3, 8)), jnp.asarray(3, jnp.int32))
    assert not rules.scatter_determinism(cw, batched=True)


def test_scatter_determinism_allows_masked_scratch_redirect():
    """The round-9 masked-store idiom: disabled lanes select ONE
    dedicated scratch slot, so colliding "writes" all carry the same
    redirect — masked by construction."""
    def masked(x, word, mask):
        idx = jnp.where(mask, word, 16)
        return x.at[idx].set(1.0, mode="drop")

    cm = jax.make_jaxpr(jax.vmap(masked))(
        jnp.zeros((3, 17)), jnp.zeros((3, 4), jnp.int32),
        jnp.zeros((3, 4), bool))
    assert not rules.scatter_determinism(cm, batched=True)


def test_scatter_determinism_single_row_is_trivially_safe():
    """A lone index row cannot collide with itself: size-1 row axes
    (and rank-1 indices whose only row axis is a vmap batching dim)
    are out of scope even when the index value is fully opaque."""
    def one_row(x, i, v):
        return x.at[i.reshape(1)].set(v)

    cv = jax.make_jaxpr(jax.vmap(one_row))(
        jnp.zeros((3, 16)), jnp.zeros((3,), jnp.int32),
        jnp.zeros((3,)))
    assert not rules.scatter_determinism(cv, batched=True)

    c1 = jax.make_jaxpr(
        lambda x, i: x.at[i.reshape(1)].set(1.0))(
        jnp.zeros(16), jnp.asarray(5, jnp.int32))
    assert not rules.scatter_determinism(c1, batched=True)


def test_scatter_determinism_masked_redirect_needs_all_operands():
    """A masked redirect combined with an OPAQUE operand is not the
    round-9 idiom: `base + where(mask, 0, S)` still collides at the
    base rows, and an opaque array concatenated next to a masked one
    can alias it — the pass-through must require EVERY non-uniform
    operand to be the masked select, not any one of them."""
    def bad_add(x, base, mask):
        idx = base + jnp.where(mask, 0, 16)
        return x.at[idx].set(1.0, mode="drop")

    ca = jax.make_jaxpr(jax.vmap(bad_add))(
        jnp.zeros((3, 32)), jnp.zeros((3, 4), jnp.int32),
        jnp.zeros((3, 4), bool))
    assert rules.scatter_determinism(ca, batched=True)

    def bad_cat(x, word, opaque, mask):
        idx = jnp.concatenate([jnp.where(mask, word, 16), opaque])
        return x.at[idx].set(1.0, mode="drop")

    cc = jax.make_jaxpr(jax.vmap(bad_cat))(
        jnp.zeros((3, 17)), jnp.zeros((3, 4), jnp.int32),
        jnp.zeros((3, 4), jnp.int32), jnp.zeros((3, 4), bool))
    assert rules.scatter_determinism(cc, batched=True)

    # a select whose SIBLING arm is fully opaque is not the idiom
    # either: lanes picking the opaque arm can still collide
    def bad_sel(x, word, opaque, p, mask):
        idx = jnp.where(p, opaque, jnp.where(mask, word, 16))
        return x.at[idx].set(1.0, mode="drop")

    cs = jax.make_jaxpr(jax.vmap(bad_sel))(
        jnp.zeros((3, 17)), jnp.zeros((3, 4), jnp.int32),
        jnp.zeros((3, 4), jnp.int32), jnp.zeros((3, 4), bool),
        jnp.zeros((3, 4), bool))
    assert rules.scatter_determinism(cs, batched=True)


def test_scatter_determinism_const_tables_and_row_axis_limits():
    """A hoisted no-repeat host const index table is collision-free
    (the device_put between the constvar and its use must not hide
    it), but per-axis distinctness proofs stop at ONE multi-size row
    axis: [[0, 1], [1, 0]] is distinct along both axes yet rows (0,0)
    and (1,1) both hold index 0."""
    import numpy as np

    def ok_tbl(x, v):
        return x.at[jnp.asarray(np.arange(4, dtype=np.int32))].set(v)

    ct = jax.make_jaxpr(jax.vmap(ok_tbl))(
        jnp.zeros((3, 16)), jnp.zeros((3, 4)))
    assert not rules.scatter_determinism(ct, batched=True)

    def bad_tbl(x, v):
        tbl = jnp.asarray(np.array([[0, 1], [1, 0]], np.int32))
        return x.at[tbl].set(v)

    c2 = jax.make_jaxpr(jax.vmap(bad_tbl))(
        jnp.zeros((3, 16)), jnp.zeros((3, 2, 2)))
    assert rules.scatter_determinism(c2, batched=True)


# ---- the real configs must pass -------------------------------------------


def test_audit_default_programs_clean():
    """The acceptance gate: gated, ungated, shl2, sweep B=4, the
    telemetry-recording gated engine, the combined sweep+telemetry
    campaign, the 2D batch x tile campaign (round 18), the
    multi-domain DVFS campaign (round 19), the histogram-recording
    gated engine (round 21) AND the per-phase-gated 2D campaign
    (round 22) all pass every rule — the same call
    `tools/regress.py --smoke` and
    `python -m graphite_tpu.tools.audit` make."""
    report = audit(tiles=8)
    assert {r.program for r in report.results} == {
        "gated-msi", "ungated-msi", "shl2-mesi", "sweep-b4",
        "gated-msi-tel", "sweep-b4-tel", "sweep-b4-2d", "sweep-b4-dvfs",
        "gated-msi-hist", "gated-msi-2d"}
    # the sweep programs must get the knob-fold rule, the others not
    by_prog = {}
    for r in report.results:
        by_prog.setdefault(r.program, set()).add(r.rule)
    assert "knob-fold" in by_prog["sweep-b4"]
    assert "knob-fold" in by_prog["sweep-b4-tel"]
    # the 2D campaign's knobs must stay live THROUGH the shard_map
    # call boundary — knob-fold runs (and passes) on the composition
    assert "knob-fold" in by_prog["sweep-b4-2d"]
    # the round-19 multi-domain campaign keeps sync_delay_cycles AND
    # dvfs_domain_mhz live — knob-fold runs (and passes) on it, and
    # the dvfs-off lint covers every default program WITHOUT a spec
    assert "knob-fold" in by_prog["sweep-b4-dvfs"]
    assert "dvfs-off" in by_prog["sweep-b4"]
    assert "dvfs-off" not in by_prog["sweep-b4-dvfs"]
    assert "knob-fold" not in by_prog["gated-msi"]
    # the combined campaign records telemetry, so the telemetry-off
    # lint must NOT run on it (the ring is policed via cond-payload)
    assert "telemetry-off" not in by_prog["sweep-b4-tel"]
    assert "telemetry-off" in by_prog["sweep-b4"]
    # the round-21 histogram program records, so the hist-off lint
    # must NOT run on it; every spec-less program gets it
    assert "hist-off" not in by_prog["gated-msi-hist"]
    assert "hist-off" in by_prog["gated-msi"]
    assert report.ok and not report.findings, "\n".join(
        str(f) for f in report.findings)


def test_default_programs_subset_and_unknown():
    with pytest.raises(ValueError, match="unknown program"):
        default_programs(4, names=["nope"])


def test_memoryless_sweep_audits_clean():
    """Memoryless campaigns never read the memory knobs by design
    (Knobs.from_params zeroes them) — the knob-fold required set must
    shrink to the knobs that CAN enter the program."""
    from graphite_tpu.analysis.audit import audit_program, \
        spec_from_sweep
    from graphite_tpu.sweep import SweepRunner

    bs = []
    for _ in range(4):
        b = TraceBuilder()
        for _ in range(6):
            b.instr(Op.IALU)
        bs.append(b)
    tr = TraceBatch.from_builders(bs)
    cfg = """
[general]
total_cores = 4
mode = lite
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    runner = SweepRunner(SimConfig(ConfigFile.from_string(cfg)),
                         [tr, tr])
    spec = spec_from_sweep("memoryless", runner, max_quanta=256)
    assert sorted(spec.knob_invars) == ["quantum_ps"]
    results = audit_program(spec)
    assert all(r.ok for r in results), [
        str(f) for r in results for f in r.findings]


def test_barrier_host_program_audits_clean():
    """lower() must hand the auditor the artifact run() executes: for
    barrier_host sims that is the batched host-dispatch region.  With
    the whole-engine mem_gate ON the gate cond legitimately carries
    the memory state (its size ceiling IS the design), so the
    forbidden-store set empties; with mem_gate forced off the delta
    plans must hold in this program too."""
    from graphite_tpu.analysis.audit import audit_program, \
        spec_from_simulator
    from graphite_tpu.engine.simulator import Simulator

    sc = SimConfig(ConfigFile.from_string(config_text(
        8, shared_mem=True, clock_scheme="lax_barrier")))
    batch = synthetic.memory_stress_trace(
        8, n_accesses=8, working_set_bytes=1 << 10,
        write_fraction=0.4, shared_fraction=0.5, seed=1)
    sim = Simulator(sc, batch, barrier_host=True, barrier_batch=4)
    assert sim.params.mem_gate
    spec = spec_from_simulator("bh-gate", sim, max_quanta=256)
    assert spec.forbidden_cond_avals == ()
    assert all(r.ok for r in audit_program(spec))
    sim2 = Simulator(sc, batch, barrier_host=True, barrier_batch=4,
                     phase_gate=True, mem_gate_bytes=0)
    spec2 = spec_from_simulator("bh-nogate", sim2, max_quanta=256)
    assert spec2.forbidden_cond_avals
    results = audit_program(spec2)
    assert all(r.ok for r in results), [
        str(f) for r in results for f in r.findings]


# ---- trace validation -----------------------------------------------------


def _exit_all(builders):
    return TraceBatch.from_builders(builders)


class TestTraceValidation:
    def test_unmatched_recv_fails(self):
        b0, b1 = TraceBuilder(), TraceBuilder()
        b0.recv(1)          # tile 1 never sends
        b1.instr(Op.IALU)
        with pytest.raises(TraceValidationError,
                           match="guaranteed deadlock"):
            validate_batch(_exit_all([b0, b1]))

    def test_any_sender_recv_counts_against_total(self):
        b0, b1 = TraceBuilder(), TraceBuilder()
        b0.recv(-1)         # wildcard, but nobody sends to tile 0
        b1.instr(Op.IALU)
        with pytest.raises(TraceValidationError, match="RECV more"):
            validate_batch(_exit_all([b0, b1]))

    def test_matched_send_recv_passes(self):
        b0, b1 = TraceBuilder(), TraceBuilder()
        b0.send(1)
        b1.recv(0)
        b1.send(0)
        b0.recv(-1)
        assert validate_batch(_exit_all([b0, b1])) == []

    def test_send_out_of_range_fails(self):
        b0, b1 = TraceBuilder(), TraceBuilder()
        b0.send(7)          # only 2 tiles
        b1.instr(Op.IALU)
        with pytest.raises(TraceValidationError, match="outside"):
            validate_batch(_exit_all([b0, b1]))

    def test_bad_opcode_fails(self):
        b0, b1 = TraceBuilder(), TraceBuilder()
        b0.instr(Op.IALU)
        b1.instr(Op.IALU)
        batch = _exit_all([b0, b1])
        batch.op[0, 0] = 200    # not an Op
        with pytest.raises(TraceValidationError, match="opcodes"):
            validate_batch(batch)

    def test_barrier_short_count_fails(self):
        bs = [TraceBuilder() for _ in range(4)]
        bs[0].barrier_init(3, 3)
        for b in bs[:2]:        # only 2 of 3 participants ever wait
            b.barrier_wait(3)
        with pytest.raises(TraceValidationError, match="stranded"):
            validate_batch(_exit_all(bs))

    def test_barrier_uninitialized_fails(self):
        bs = [TraceBuilder() for _ in range(2)]
        for b in bs:
            b.barrier_wait(5)
        with pytest.raises(TraceValidationError, match="never"):
            validate_batch(_exit_all(bs))

    def test_barrier_inconsistent_count_fails(self):
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(1, 2)
        bs[1].barrier_init(1, 1)
        for b in bs:
            b.barrier_wait(1)
        with pytest.raises(TraceValidationError, match="inconsistent"):
            validate_batch(_exit_all(bs))

    def test_barrier_count_out_of_range_fails(self):
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(1, 9)   # > n_tiles
        for b in bs:
            b.barrier_wait(1)
        with pytest.raises(TraceValidationError, match="outside"):
            validate_batch(_exit_all(bs))

    def test_barrier_id_out_of_range_fails(self):
        """The engine CLIPS barrier ids, so an out-of-range id aliases
        another barrier — reject before the per-id analysis lies."""
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(-1, 2)
        for b in bs:
            b.barrier_wait(-1)
        with pytest.raises(TraceValidationError, match="aliasing"):
            validate_batch(_exit_all(bs))
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(70, 2)
        for b in bs:
            b.barrier_wait(70)
        with pytest.raises(TraceValidationError, match="aliasing"):
            validate_batch(_exit_all(bs), n_barriers=64)
        # in range with the bound supplied: fine
        assert validate_batch(_exit_all(bs), n_barriers=128) == []

    def test_barrier_sync_generation_beyond_releases_fails(self):
        """Engine semantics: BARRIER_SYNC #g blocks until barrier_gen
        reaches g, and barrier_gen only advances arrivals // count
        times — a sync past that is a provable deadlock."""
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(1, 2)
        bs[0].barrier_arrive(1)
        bs[1].barrier_arrive(1)        # 2 arrivals / count 2 -> 1 release
        bs[0].barrier_sync(1, 2)       # waits for release #2
        with pytest.raises(TraceValidationError,
                           match="generation 2"):
            validate_batch(_exit_all(bs))

    def test_barrier_sync_satisfied_generation_passes(self):
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(1, 2)
        bs[0].barrier_arrive(1)
        bs[1].barrier_arrive(1)
        bs[0].barrier_sync(1, 1)
        assert [f for f in validate_batch(_exit_all(bs))
                if f.severity == "error"] == []

    def test_mixed_arrive_remainder_warns_not_raises(self):
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].barrier_init(1, 2)
        bs[0].barrier_arrive(1)    # 1 arrival, count 2, non-blocking
        fs = validate_batch(_exit_all(bs))
        assert fs and all(f.severity == "warning" for f in fs)

    def test_valid_workloads_pass(self):
        batch = synthetic.memory_stress_trace(
            8, n_accesses=24, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.5, seed=3)
        assert validate_batch(batch) == []
        from graphite_tpu.trace.benchmarks import BENCHMARKS

        fft = BENCHMARKS["fft"](8, points_per_tile=16)
        assert [f for f in validate_batch(fft)
                if f.severity == "error"] == []

    def test_pack_traces_validates_and_names_sim(self):
        from graphite_tpu.sweep.pack import pack_traces

        good = synthetic.memory_stress_trace(
            4, n_accesses=8, working_set_bytes=1 << 10,
            write_fraction=0.4, shared_fraction=0.5, seed=1)
        b0 = TraceBuilder()
        b0.recv(1)
        bad = _exit_all([b0] + [TraceBuilder() for _ in range(3)])
        with pytest.raises(TraceValidationError, match="sim 1"):
            pack_traces([good, bad])
        # escape hatch for deliberately pathological traces
        pack = pack_traces([good, bad], validate=False)
        assert pack.n_sims == 2
