"""Co-located thread synchronization (VERDICT round-1 weak #4).

Threads sharing a tile serialize onto one engine lane; the live
frontend's completion-time recording + split sync ops
(BARRIER_ARRIVE/SYNC, COND_JOIN — `trace/schema.py`) make barriers,
condvars, mutexes, and CAPI pairs work between co-located threads (the
reference's ThreadScheduler allows arbitrary sync among queued threads,
`thread_scheduler.cc`).  Replays are also cross-checked against the
golden interpreter, which implements the split ops independently.
"""

import numpy as np

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.frontend import (
    CAPI_message_receive_w,
    CAPI_message_send_w,
    CarbonApp,
    CarbonBarrier,
    CarbonCond,
    CarbonMutex,
    carbon_join_thread,
    carbon_spawn_thread,
    carbon_work,
)
from graphite_tpu.golden import run_golden
from graphite_tpu.trace.schema import TraceBatch, TraceBuilder


def make_config(n_tiles):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
ialu = 1
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def run_app(n_tiles, main, max_threads=None):
    app = CarbonApp(make_config(n_tiles), max_threads=max_threads)
    batch = app.start(main)
    res = app.run()
    return app, batch, res


class TestColocatedBarrier:
    def test_barrier_across_colocated_threads(self):
        """3 threads on 1 tile + 1 on the other meet at one barrier."""
        hits = []

        def worker(bar):
            carbon_work(5)
            bar.wait()
            carbon_work(3)
            hits.append(1)

        def main():
            bar = CarbonBarrier(4)
            ts = [carbon_spawn_thread(worker, bar) for _ in range(3)]
            bar.wait()
            carbon_work(2)
            for t in ts:
                carbon_join_thread(t)
            hits.append(1)

        app, batch, res = run_app(2, main)
        assert len(hits) == 4
        assert (np.asarray(res.clock_ps) > 0).all()
        # at least two worker threads shared tile 1's lane
        assert res.sync_instructions.sum() >= 1

    def test_repeated_barrier_generations(self):
        """The generation rendezvous survives barrier reuse."""

        def worker(bar, rounds):
            for _ in range(rounds):
                carbon_work(4)
                bar.wait()

        def main():
            bar = CarbonBarrier(3)
            ts = [carbon_spawn_thread(worker, bar, 5) for _ in range(2)]
            for _ in range(5):
                carbon_work(2)
                bar.wait()
            for t in ts:
                carbon_join_thread(t)

        app, batch, res = run_app(2, main)
        assert (np.asarray(res.clock_ps) > 0).all()


class TestColocatedCond:
    def test_cond_between_colocated_threads(self):
        """Producer signals a condvar consumed by a co-located waiter."""
        got = []

        def consumer(mux, cond, box):
            with mux:
                while not box:
                    cond.wait()
                got.append(box.pop())

        def main():
            mux = CarbonMutex()
            cond = CarbonCond(mux)
            box = []
            t = carbon_spawn_thread(consumer, mux, cond, box)
            carbon_work(10)
            with mux:
                box.append(42)
                cond.signal()
            carbon_join_thread(t)

        app, batch, res = run_app(1, main)  # ONE tile: fully co-located
        assert got == [42]
        assert (np.asarray(res.clock_ps) > 0).all()


class TestColocatedCapiAndMutex:
    def test_capi_pair_colocated(self):
        """Send/recv between two threads on the same tile."""
        out = []

        def receiver():
            out.append(CAPI_message_receive_w(0, 0))

        def main():
            t = carbon_spawn_thread(receiver)
            carbon_work(6)
            CAPI_message_send_w(0, 0, 7)
            carbon_join_thread(t)

        app, batch, res = run_app(1, main)
        assert out == [7]
        assert (np.asarray(res.clock_ps) > 0).all()

    def test_mutex_contention_colocated(self):
        """Lock held by one co-located thread, contended by another."""
        order = []

        def worker(mux, k):
            with mux:
                carbon_work(8)
                order.append(k)

        def main():
            mux = CarbonMutex()
            ts = [carbon_spawn_thread(worker, mux, k) for k in range(3)]
            with mux:
                carbon_work(8)
            for t in ts:
                carbon_join_thread(t)

        app, batch, res = run_app(1, main)
        assert sorted(order) == [0, 1, 2]
        assert (np.asarray(res.clock_ps) > 0).all()


class TestSplitOpsGolden:
    """The split ops as trace programs, differential vs the oracle."""

    def test_arrive_sync_differential(self):
        bs = [TraceBuilder() for _ in range(3)]
        bs[0].barrier_init(0, 3)
        for r in range(4):
            for i, b in enumerate(bs):
                b.bblock(3 + i, 3 + i)
                b.barrier_arrive(0)
                b.barrier_sync(0, r + 1)
        batch = TraceBatch.from_builders(bs)
        sc = make_config(3)
        res = Simulator(sc, batch).run()
        gold = run_golden(sc, batch)
        np.testing.assert_array_equal(res.clock_ps, gold.clock_ps)
        np.testing.assert_array_equal(res.sync_instructions,
                                      gold.sync_instructions)

    def test_cond_join_differential(self):
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].cond_init(0)
        bs[0].barrier_init(1, 2)
        for b in bs:
            b.barrier_wait(1)
        # tile 0 publishes two signals; tile 1 joins each in turn
        bs[0].bblock(10, 10)
        bs[0].cond_signal(0, publish=True)
        bs[0].bblock(10, 10)
        bs[0].cond_broadcast(0, publish=True)
        bs[1].cond_join(0, 1)
        bs[1].bblock(2, 2)
        bs[1].cond_join(0, 2)
        batch = TraceBatch.from_builders(bs)
        sc = make_config(2)
        res = Simulator(sc, batch).run()
        gold = run_golden(sc, batch)
        np.testing.assert_array_equal(res.clock_ps, gold.clock_ps)

    def test_cond_join_lagging_reads_its_own_generation(self):
        """A joiner that replays after SEVERAL publishes must take its
        requested sequence's time, not the latest (per-generation ring)."""
        bs = [TraceBuilder() for _ in range(2)]
        bs[0].cond_init(0)
        bs[0].barrier_init(1, 2)
        for b in bs:
            b.barrier_wait(1)
        bs[0].bblock(10, 10)
        bs[0].cond_signal(0, publish=True)    # seq 1 at ~10 cycles
        bs[0].bblock(10, 10)
        bs[0].cond_signal(0, publish=True)    # seq 2 at ~20 cycles
        # tile 1 runs long compute first: by the time its joins replay,
        # both publishes already executed on tile 0's lane
        bs[1].bblock(100, 100)
        bs[1].cond_join(0, 1)
        bs[1].cond_join(0, 2)
        batch = TraceBatch.from_builders(bs)
        sc = make_config(2)
        res = Simulator(sc, batch).run()
        gold = run_golden(sc, batch)
        np.testing.assert_array_equal(res.clock_ps, gold.clock_ps)


class TestRotatingParticipants:
    def test_barrier_generations_with_skipping_threads(self):
        """A barrier reused by DIFFERENT thread pairs per round: the
        release generation is global, not per-thread arrival count."""
        def pair(bar):
            carbon_work(4)
            bar.wait()
            carbon_work(2)

        def main():
            bar = CarbonBarrier(2)
            # round 1: A + B; round 2: C + D (each thread waits once)
            a = carbon_spawn_thread(pair, bar)
            b = carbon_spawn_thread(pair, bar)
            carbon_join_thread(a)
            carbon_join_thread(b)
            c = carbon_spawn_thread(pair, bar)
            d = carbon_spawn_thread(pair, bar)
            carbon_join_thread(c)
            carbon_join_thread(d)

        app, batch, res = run_app(2, main, max_threads=8)
        assert (np.asarray(res.clock_ps) > 0).all()
