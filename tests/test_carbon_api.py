"""Carbon user-API frontend: live threaded apps → recorded traces → replay.

Ports the reference's app-test tier (`tests/apps/`: ping_pong, shared-memory
producer/consumer, spawn/join) from C+CAPI under Pin to Python functions
under the trace-recording frontend (SURVEY §4 tier 2).
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.frontend import (
    CAPI_message_receive_w,
    CAPI_message_send_w,
    CarbonApp,
    CarbonBarrier,
    CarbonCond,
    CarbonMutex,
    carbon_get_tile_id,
    carbon_join_thread,
    carbon_load,
    carbon_spawn_thread,
    carbon_store,
    carbon_work,
)


def make_config(n_tiles, shared_mem=False):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = {"true" if shared_mem else "false"}
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


class TestPingPong:
    def test_ping_pong(self):
        """`tests/apps/ping_pong` analog: a token bounces N times."""
        N = 8
        app = CarbonApp(make_config(2))

        def pong():
            for i in range(N):
                tok = CAPI_message_receive_w(0, 1)
                carbon_work(10)
                CAPI_message_send_w(1, 0, tok + 1)

        def main():
            t = carbon_spawn_thread(pong)
            tok = 0
            for i in range(N):
                CAPI_message_send_w(0, 1, tok)
                tok = CAPI_message_receive_w(1, 0)
            assert tok == N
            carbon_join_thread(t)

        app.start(main)
        res = app.run()
        assert res.func_errors == 0
        assert res.recv_instructions[0] >= 1
        # both tiles moved through N round trips of work
        assert res.clock_ps[1] > 0


class TestSpawnJoinMutex:
    def test_mutex_counter(self):
        """N workers increment a shared counter under a mutex.  The live
        execution asserts the count; the replay re-runs the loads/stores
        through the coherence engine unchecked (mutex-ordered values are
        not replay-checkable — grant order follows simulated time)."""
        T, ITERS = 4, 5
        app = CarbonApp(make_config(T, shared_mem=True))
        ADDR = 0x1000

        def worker(mux):
            for _ in range(ITERS):
                with mux:
                    v = carbon_load(ADDR)
                    carbon_work(3)
                    carbon_store(ADDR, v + 1)

        def main():
            mux = CarbonMutex()
            carbon_store(ADDR, 0)
            tids = [carbon_spawn_thread(worker, mux) for _ in range(T - 1)]
            worker(mux)
            for t in tids:
                carbon_join_thread(t)
            assert carbon_load(ADDR) == T * ITERS

        app.start(main)
        res = app.run()
        assert res.func_errors == 0

    def test_join_returns_after_worker(self):
        app = CarbonApp(make_config(2))
        done = []

        def worker():
            carbon_work(100)
            done.append(carbon_get_tile_id())

        def main():
            t = carbon_spawn_thread(worker)
            carbon_join_thread(t)
            assert done == [1]

        app.start(main)
        res = app.run()
        # joiner's clock pinned at worker exit (100 cycles) or later
        assert res.clock_ps[0] >= res.clock_ps[1]


class TestCondVar:
    def test_producer_consumer(self):
        app = CarbonApp(make_config(2, shared_mem=True))
        ADDR = 0x2000

        def consumer(mux, cond):
            mux.lock()
            while carbon_load(ADDR) == 0:
                cond.wait()
            v = carbon_load(ADDR)
            mux.unlock()
            assert v == 7

        def main():
            mux = CarbonMutex()
            cond = CarbonCond(mux)
            carbon_store(ADDR, 0)
            t = carbon_spawn_thread(consumer, mux, cond)
            carbon_work(50)
            mux.lock()
            carbon_store(ADDR, 7)
            cond.signal()
            mux.unlock()
            carbon_join_thread(t)

        app.start(main)
        res = app.run()
        assert res.func_errors == 0


class TestBarrierAndMemory:
    def test_barrier_fan(self):
        """All tiles compute, hit a barrier, then read each other's data
        (`tests/unit/shared_mem_test*` pattern, live)."""
        T = 4
        app = CarbonApp(make_config(T, shared_mem=True))

        def worker(bar):
            me = carbon_get_tile_id()
            carbon_store(0x100 * (me + 1), me * 11)
            carbon_work(me * 7 + 1)
            bar.wait()
            nxt = (me + 1) % T
            assert carbon_load(0x100 * (nxt + 1), check=True) == nxt * 11

        def main():
            bar = CarbonBarrier(T)
            tids = [carbon_spawn_thread(worker, bar) for _ in range(T - 1)]
            worker(bar)
            for t in tids:
                carbon_join_thread(t)

        app.start(main)
        res = app.run()
        assert res.func_errors == 0
        assert res.sync_instructions.sum() >= 0

    def test_oversubscription_queues(self):
        """More threads than tiles: the scheduler queues them per tile and
        runs each when the occupant exits (cooperative scheme)."""
        from graphite_tpu.frontend import carbon_yield

        T = 2
        app = CarbonApp(make_config(T))
        ran = []

        def worker(i):
            carbon_work(10)
            ran.append(i)

        def main():
            tids = [carbon_spawn_thread(worker, i) for i in range(4)]
            carbon_yield()  # main alone on tile 0 queue: no-op rotation
            for t in tids:
                carbon_join_thread(t)
            assert sorted(ran) == [0, 1, 2, 3]

        app.start(main)
        res = app.run()
        assert res.func_errors == 0

    def test_join_queued_target_same_tile(self):
        """Joining a thread queued behind the joiner on its own tile must
        not deadlock: the join releases the core (stallThread semantics)."""
        T = 1
        app = CarbonApp(make_config(T))
        done = []

        def worker():
            carbon_work(10)
            done.append(1)

        def main():
            t = carbon_spawn_thread(worker)  # queued behind main on tile 0
            carbon_work(5)
            carbon_join_thread(t)
            assert done == [1]
            carbon_work(5)

        app.start(main)
        res = app.run()
        assert res.func_errors == 0
        assert res.instruction_count[0] == 20  # all segments on tile 0

    def test_blocking_primitives_release_core(self):
        """Barrier waits are scheduling points: a co-located queued thread
        runs *while* the occupant blocks (stallThread semantics).  Proof by
        construction: worker_a (tile 1) refuses to reach the barrier until
        worker_b — queued behind main on tile 0 — has run; without the core
        release this deadlocks."""
        import threading

        app = CarbonApp(make_config(2))
        b_ran = threading.Event()

        def worker_a(bar):
            assert b_ran.wait(timeout=30)
            bar.wait()

        def worker_b():
            carbon_work(10)
            b_ran.set()

        def main():
            bar = CarbonBarrier(2)
            ta = carbon_spawn_thread(worker_a, bar)   # tile 1
            tb = carbon_spawn_thread(worker_b)        # queued on tile 0
            bar.wait()  # must release tile 0's core so worker_b can run
            carbon_join_thread(ta)
            carbon_join_thread(tb)

        app.start(main)
        res = app.run()
        assert res.func_errors == 0

    def test_affinity_placement(self):
        from graphite_tpu.frontend import carbon_get_affinity

        T = 4
        app = CarbonApp(make_config(T))
        seen = []

        def worker():
            seen.append(carbon_get_tile_id())

        def main():
            t = carbon_spawn_thread(worker, affinity={2})
            carbon_join_thread(t)
            assert seen == [2]
            assert carbon_get_affinity() is None

        app.start(main)

    def test_migrate_self(self):
        from graphite_tpu.frontend import carbon_migrate_self

        T = 4
        app = CarbonApp(make_config(T))

        def main():
            assert carbon_get_tile_id() == 0
            carbon_work(5)
            carbon_migrate_self(3)
            assert carbon_get_tile_id() == 3
            carbon_work(5)

        app.start(main)
        res = app.run()
        # work recorded on both tiles' streams
        assert res.clock_ps[0] > 0 and res.clock_ps[3] > 0
