"""Config parsing + topology math vs reference semantics.

References: `common/config/` (INI surface), `common/misc/config.cc`
(tile/process math), `carbon_sim.cfg` (the canonical file must parse).
"""

import os

import pytest

from graphite_tpu.config import ConfigFile, SimConfig, SimulationMode, TileSpec
from graphite_tpu.config.config_file import ConfigError, parse_override_args
from graphite_tpu.models.network_emesh import (
    emesh_process_to_tile_mapping,
    is_tile_count_permissible,
    manhattan_distance,
    memory_controller_positions,
    mesh_dims,
)

REFERENCE_CFG = "/root/reference/carbon_sim.cfg"
if not os.path.exists(REFERENCE_CFG):
    # containers without the reference mount fall back to the vendored
    # fixture, which mirrors exactly the asserted configuration surface
    REFERENCE_CFG = os.path.join(os.path.dirname(__file__), "fixtures",
                                 "carbon_sim.cfg")


def test_parses_reference_carbon_sim_cfg():
    cfg = ConfigFile.from_file(REFERENCE_CFG)
    assert cfg.get_int("general/total_cores") == 64
    assert cfg.get_int("general/num_processes") == 1
    assert cfg.get_bool("general/enable_shared_mem") is True
    assert cfg.get_string("general/mode") == "full"
    assert cfg.get_float("general/max_frequency") == 2.0
    assert cfg.get_string("general/output_file") == "sim.out"
    assert cfg.get_int("clock_skew_management/lax_barrier/quantum") == 1000
    assert cfg.get_string("clock_skew_management/scheme") == "lax_barrier"
    assert cfg.get_int("core/static_instruction_costs/idiv") == 18
    assert cfg.get_string("caching_protocol/type") == "pr_l1_pr_l2_dram_directory_msi"
    assert cfg.get_int("l2_cache/T1/cache_size") == 512
    assert cfg.get_string("l2_cache/T1/replacement_policy") == "lru"
    assert cfg.get_string("dram_directory/total_entries") == "auto"
    assert cfg.get_string("network/user") == "emesh_hop_counter"
    # trailing comments stripped (carbon_sim.cfg:143)
    assert cfg.get_int("runtime_energy_modeling/interval") == 1000
    # quoted strings with commas (carbon_sim.cfg:151)
    assert cfg.get_string("dvfs/domains").startswith("<1.0, CORE")
    # float in scientific notation (carbon_sim.cfg:358)
    assert cfg.get_float("link_model/optical/waveguide_delay_per_mm") == 10e-3
    assert cfg.get_string("process_map/process3") == "127.0.0.1"


def test_typed_getter_errors_and_defaults():
    cfg = ConfigFile.from_string("[a/b]\nx = 5\nflag = false\n")
    assert cfg.get_int("a/b/x") == 5
    assert cfg.get_bool("a/b/flag") is False
    assert cfg.get_int("a/b/missing", 7) == 7
    with pytest.raises(ConfigError):
        cfg.get_int("a/b/missing")


def test_cli_overrides():
    rest, overrides, path = parse_override_args(
        ["prog", "--general/total_cores=16", "-c", "other.cfg", "--log/enabled=true"]
    )
    assert rest == ["prog"]
    assert path == "other.cfg"
    assert overrides.get_int("general/total_cores") == 16
    assert overrides.get_bool("log/enabled") is True
    base = ConfigFile.from_string("[general]\ntotal_cores = 64\n")
    base.merge(overrides)
    assert base.get_int("general/total_cores") == 16


def _simconfig(total=64, procs=1, mode="full", extra=""):
    text = (
        f"[general]\ntotal_cores = {total}\nnum_processes = {procs}\n"
        f"mode = {mode}\n{extra}"
    )
    return SimConfig(ConfigFile.from_string(text))


class TestTopology:
    def test_tile_count_bookkeeping_full_mode(self):
        # config.cc:77-82: +1 MCP, +1 spawner per process
        sc = _simconfig(total=64, procs=2, mode="full")
        assert sc.application_tiles == 64
        assert sc.total_tiles == 64 + 1 + 2
        assert sc.mcp_tile_id == 66
        # spawners on tiles app..total-2 (config.cc:180)
        assert sc.thread_spawner_tile_id(0) == 64
        assert sc.thread_spawner_tile_id(1) == 65
        assert sc.is_thread_spawner_tile(64)
        assert not sc.is_thread_spawner_tile(66)
        assert sc.is_application_tile(63)
        assert not sc.is_application_tile(64)

    def test_tile_count_bookkeeping_lite_mode(self):
        sc = _simconfig(total=16, procs=1, mode="lite")
        assert sc.total_tiles == 17  # +MCP only
        assert sc.thread_spawner_tile_id(0) == -1

    def test_lite_mode_single_process_only(self):
        with pytest.raises(ValueError):
            _simconfig(total=16, procs=2, mode="lite")

    def test_round_robin_striping(self):
        # config.cc:220-227
        sc = _simconfig(total=8, procs=3, mode="full")
        assert sc.process_to_tiles[0][:3] == [0, 3, 6]
        assert sc.process_to_tiles[1][:3] == [1, 4, 7]
        assert sc.process_to_tiles[2][:2] == [2, 5]
        # spawners appended per process, MCP on process 0 (config.cc:177-193)
        assert sc.process_to_tiles[0][-1] == sc.mcp_tile_id
        assert sc.tile_to_process[sc.mcp_tile_id] == 0
        assert sc.tile_to_process[sc.thread_spawner_tile_id(2)] == 2

    def test_model_list_parsing(self):
        # config.cc:365-472 / carbon_sim.cfg:158-176
        sc = _simconfig(
            total=8,
            extra='[tile]\nmodel_list = "<2,iocoom,T1,T1,T1>, <6,simple,default,default,default>"\n',
        )
        assert sc.tile_spec(0).core_type == "iocoom"
        assert sc.tile_spec(1).core_type == "iocoom"
        assert sc.tile_spec(2).core_type == "simple"
        assert sc.tile_spec(7).core_type == "simple"
        # MCP/spawner tiles get defaults (config.cc:466-471)
        assert sc.tile_spec(sc.mcp_tile_id) == TileSpec()

    def test_model_list_count_mismatch(self):
        with pytest.raises(ValueError):
            _simconfig(total=8, extra='[tile]\nmodel_list = "<4,iocoom>"\n')

    def test_reference_cfg_end_to_end(self):
        cfg = ConfigFile.from_file(REFERENCE_CFG)
        sc = SimConfig(cfg)
        assert sc.mode == SimulationMode.FULL
        assert sc.application_tiles == 64
        assert sc.total_tiles == 66
        assert sc.tile_spec(0).core_type == "iocoom"
        assert sc.network_types[0] == "emesh_hop_counter"
        assert sc.network_types[2] == "magic"  # SYSTEM always magic
        assert sc.max_frequency_mhz == 2000
        assert len(sc.process_map_hosts()) == 1


class TestEMeshTopology:
    def test_mesh_dims(self):
        # network_model_emesh_hop_by_hop.cc:286-287,308-320
        assert mesh_dims(64) == (8, 8)
        assert mesh_dims(12) == (3, 4)
        assert is_tile_count_permissible(64)
        assert is_tile_count_permissible(12)
        assert not is_tile_count_permissible(7)

    def test_manhattan_distance(self):
        assert manhattan_distance(0, 63, 8) == 14
        assert manhattan_distance(0, 1, 8) == 1
        assert manhattan_distance(9, 9, 8) == 0

    def test_memory_controller_positions(self):
        pos = memory_controller_positions(4, 64)
        assert len(pos) == 4
        assert len(set(pos)) == 4
        assert all(0 <= p < 64 for p in pos)

    def test_process_mapping_partitions_all_tiles(self):
        for tiles, procs in [(64, 4), (64, 2), (16, 3), (64, 1), (1024, 8)]:
            mapping = emesh_process_to_tile_mapping(tiles, procs)
            seen = sorted(t for tl in mapping for t in tl)
            assert seen == list(range(tiles)), (tiles, procs)

    def test_process_mapping_is_contiguous_blocks(self):
        mapping = emesh_process_to_tile_mapping(64, 4)
        # process 0 owns the lower-left 4x4 quadrant
        assert sorted(mapping[0]) == [
            x + y * 8 for y in range(4) for x in range(4)
        ]

    def test_impermissible_tile_count_rejected(self):
        # config.cc:87-90: mesh models abort on non-factorable tile counts
        with pytest.raises(ValueError, match="mesh"):
            _simconfig(
                total=7, procs=1, mode="full",
                extra="[network]\nuser = emesh_hop_by_hop\nmemory = emesh_hop_by_hop\n",
            )

    def test_simconfig_uses_emesh_mapping(self):
        sc = _simconfig(
            total=64, procs=4, mode="full",
            extra="[network]\nuser = emesh_hop_by_hop\nmemory = emesh_hop_by_hop\n",
        )
        assert sorted(sc.process_to_tiles[0][:-2]) == [
            x + y * 8 for y in range(4) for x in range(4)
        ]
