"""Real captured execution -> npz -> full-stack replay (tools/capture_fft).

The reference's benchmark tier runs real binaries under Pin; the TPU
frontend's equivalent evidence is a real program (an actual parallel
radix-2 FFT, not a skeleton) recorded instruction-by-instruction and
replayed through the coherence engine with functional checking.
"""

import numpy as np

from graphite_tpu.tools.capture_fft import (
    measured_mix, run_fft_app, verify_numerics,
)


def test_captured_fft_is_numerically_real():
    """The captured program computes a correct FFT (it is a real
    execution, not a synthetic mix)."""
    batch, x_c, out = run_fft_app(n_tiles=4, n_points=64)
    err = verify_numerics(x_c, out, 64)
    assert err < 1e-3, f"captured FFT numerically wrong: {err}"


def test_captured_fft_replays_through_coherence(tmp_path):
    """npz round trip + replay through the full MSI stack: every
    barrier-separated load is FLAG_CHECKed against the live value, so
    the coherence engine must reproduce the real program's dataflow."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace.io import load_trace_npz, save_trace_npz

    batch, _, _ = run_fft_app(n_tiles=4, n_points=64)
    p = tmp_path / "fft.npz"
    save_trace_npz(str(p), batch)
    batch2 = load_trace_npz(str(p))

    sc = SimConfig(ConfigFile.from_string(config_text(
        4, shared_mem=True, clock_scheme="lax")))
    res = Simulator(sc, batch2).run()
    assert res.func_errors == 0
    assert int(np.asarray(res.mem_counters["l2_misses"]).sum()) > 0
    assert res.total_instructions > 0


def test_measured_mix_matches_calibration():
    """The skeleton calibration constants come from this measurement:
    10 fp per butterfly (4 FMUL + 6 FALU), ~8-9 memory refs."""
    batch, _, _ = run_fft_app(n_tiles=4, n_points=64)
    mix = measured_mix(batch)
    stages = 6
    butterflies = 32 * stages
    assert (mix["fmul"] + mix["falu"]) / butterflies == 10.0
    assert mix["fmul"] / butterflies == 4.0
    refs = (mix["loads"] + mix["stores"]) / butterflies
    assert 8.0 <= refs <= 9.0
