"""Real captured execution -> npz -> full-stack replay (tools/capture_fft).

The reference's benchmark tier runs real binaries under Pin; the TPU
frontend's equivalent evidence is a real program (an actual parallel
radix-2 FFT, not a skeleton) recorded instruction-by-instruction and
replayed through the coherence engine with functional checking.
"""

import numpy as np

from graphite_tpu.tools.capture_fft import (
    measured_mix, run_fft_app, verify_numerics,
)


def test_captured_fft_is_numerically_real():
    """The captured program computes a correct FFT (it is a real
    execution, not a synthetic mix)."""
    batch, x_c, out = run_fft_app(n_tiles=4, n_points=64)
    err = verify_numerics(x_c, out, 64)
    assert err < 1e-3, f"captured FFT numerically wrong: {err}"


def test_captured_fft_replays_through_coherence(tmp_path):
    """npz round trip + replay through the full MSI stack: every
    barrier-separated load is FLAG_CHECKed against the live value, so
    the coherence engine must reproduce the real program's dataflow."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace.io import load_trace_npz, save_trace_npz

    batch, _, _ = run_fft_app(n_tiles=4, n_points=64)
    p = tmp_path / "fft.npz"
    save_trace_npz(str(p), batch)
    batch2 = load_trace_npz(str(p))

    sc = SimConfig(ConfigFile.from_string(config_text(
        4, shared_mem=True, clock_scheme="lax")))
    res = Simulator(sc, batch2).run()
    assert res.func_errors == 0
    assert int(np.asarray(res.mem_counters["l2_misses"]).sum()) > 0
    assert res.total_instructions > 0


def test_measured_mix_matches_calibration():
    """The skeleton calibration constants come from this measurement:
    10 fp per butterfly (4 FMUL + 6 FALU), ~8-9 memory refs."""
    batch, _, _ = run_fft_app(n_tiles=4, n_points=64)
    mix = measured_mix(batch)
    stages = 6
    butterflies = 32 * stages
    assert (mix["fmul"] + mix["falu"]) / butterflies == 10.0
    assert mix["fmul"] / butterflies == 4.0
    refs = (mix["loads"] + mix["stores"]) / butterflies
    assert 8.0 <= refs <= 9.0


# ---- generalized harness: RADIX and LU (tools/capture.py) ------------------


def test_captured_radix_sorts_and_replays():
    """The captured program is a REAL parallel LSD radix sort: its
    output equals numpy's sort, and the replay reproduces every
    barrier-separated cross-tile read (histogram/rank/permutation
    sharing) through the coherence engine."""
    from graphite_tpu.tools.capture import replay_report, run_radix_app

    batch, keys, out = run_radix_app(n_tiles=4, keys_per_tile=64,
                                     radix=16, n_digits=2)
    assert (np.sort(keys) == out).all()
    rep = replay_report(batch, 4)
    assert rep["func_errors"] == 0
    assert rep["l2_misses"] > 0


def test_captured_lu_factors_and_replays():
    """The captured program is a REAL blocked LU factorization: L@U
    reconstructs the input within fixed-point tolerance, and the replay
    reproduces the diagonal/perimeter block read-sharing."""
    from graphite_tpu.tools.capture import (
        replay_report, run_lu_app, verify_lu,
    )

    batch, a0, lu = run_lu_app(n_tiles=4, n=16, block=4)
    assert verify_lu(a0, lu) < 5e-2
    rep = replay_report(batch, 4)
    assert rep["func_errors"] == 0
    assert rep["l2_misses"] > 0


def test_radix_calibration_matches_skeleton():
    """The radix skeleton's calibrated per-key costs track the measured
    capture within a loose band (the calibration source)."""
    from graphite_tpu.tools.capture import measured_mix, run_radix_app

    batch, keys, _ = run_radix_app(n_tiles=4, keys_per_tile=64,
                                   radix=16, n_digits=2)
    mix = measured_mix(batch)
    per_key_pass = mix["records"] / len(keys) / 2
    # measured 7.0 at 1024 keys; smaller runs carry relatively more
    # per-digit/barrier overhead
    assert 5.5 < per_key_pass < 11.0, per_key_pass
