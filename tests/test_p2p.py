"""lax_p2p clock-skew scheme: random pairwise clamping.

Reference `lax_p2p_sync_client.h:13-83` + `carbon_sim.cfg:99-108`: each
thread periodically picks a random partner and sleeps while it is more
than `slack` ahead.  In this engine the scheme is a per-iteration advance
mask (scheduling), not a timing model — sync decisions are
simulated-time-ordered, so results must be IDENTICAL across schemes; what
the scheme changes is how far tiles' clocks may drift apart while the
simulation runs (the reference's motivation: bounding memory growth and
timing raciness of far-ahead threads).
"""

import jax
import jax.numpy as jnp
import numpy as np

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.engine.step import subquantum_iteration
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles, scheme, slack_ns=100):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[network]
user = magic
memory = magic
[core/static_instruction_costs]
ialu = 1
imul = 100
[clock_skew_management]
scheme = {scheme}
[clock_skew_management/lax_barrier]
quantum = 1000
[clock_skew_management/lax_p2p]
slack = {slack_ns}
"""
    return SimConfig(ConfigFile.from_string(text))


def skewed_trace(n_records=400):
    """Tile 0 runs 1-cycle records, tile 1 100-cycle records: under lax,
    tile 0 races ~100x ahead."""
    b0, b1 = TraceBuilder(), TraceBuilder()
    for _ in range(n_records):
        b0.instr(Op.IALU)
        b1.instr(Op.IMUL)
    return TraceBatch.from_builders([b0, b1])


def run_skew_trajectory(sc, batch, iters=300):
    """Step manually, recording the clock spread between running tiles."""
    sim = Simulator(sc, batch)
    step = jax.jit(lambda st: subquantum_iteration(
        sim.params, sim.device_trace, st, jnp.asarray(2**61, jnp.int64))[0])
    st = sim.state
    max_skew = 0
    for _ in range(iters):
        st = step(st)
        done = np.asarray(st.done)
        if done.all():
            break
        clocks = np.asarray(st.core.clock_ps)[~done]
        if len(clocks) >= 2:
            max_skew = max(max_skew, int(clocks.max() - clocks.min()))
    return max_skew


def test_p2p_bounds_skew():
    batch = skewed_trace()
    slack_ps = 100_000  # 100 ns
    skew_p2p = run_skew_trajectory(
        make_config(2, "lax_p2p", slack_ns=100), batch)
    skew_lax = run_skew_trajectory(make_config(2, "lax"), batch)
    # p2p: held within slack + one record's cost (100 cycles = 100000 ps)
    assert skew_p2p <= slack_ps + 100_000, skew_p2p
    # lax: runs away far beyond the slack
    assert skew_lax > 4 * (slack_ps + 100_000), skew_lax


def test_p2p_results_match_lax():
    """Deterministic engine: the scheme must not change simulated results
    (unlike the reference, where scheme-dependent raciness is expected)."""
    from graphite_tpu.trace import synthetic

    batch = synthetic.message_ring_batch(4, n_rounds=6, compute_per_round=9)
    res_lax = Simulator(make_config(4, "lax"), batch).run()
    res_p2p = Simulator(make_config(4, "lax_p2p"), batch).run()
    res_bar = Simulator(make_config(4, "lax_barrier"), batch).run()
    np.testing.assert_array_equal(res_lax.clock_ps, res_p2p.clock_ps)
    np.testing.assert_array_equal(res_lax.clock_ps, res_bar.clock_ps)
    np.testing.assert_array_equal(res_lax.instruction_count,
                                  res_p2p.instruction_count)


def test_p2p_completes_under_contention():
    """A mutex workload completes and matches lax under p2p scheduling."""
    bs = [TraceBuilder() for _ in range(4)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(1, 4)
    for b in bs:
        b.barrier_wait(1)
    for r in range(12):
        t = r % 4
        bs[t].mutex_lock(0)
        bs[t].instr(Op.IMUL)
        bs[t].mutex_unlock(0)
    batch = TraceBatch.from_builders(bs)
    res_p2p = Simulator(make_config(4, "lax_p2p"), batch).run()
    res_lax = Simulator(make_config(4, "lax"), batch).run()
    np.testing.assert_array_equal(res_lax.clock_ps, res_p2p.clock_ps)
