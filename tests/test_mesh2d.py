"""2D batch x tile campaigns (round 18).

The Mesh(('batch', 'tile')) program: each device holds a tile block of
a subset of sims, the round-12 packed per-phase exchange runs over the
tile axis only, batch stays embarrassingly parallel.  Pinned here:
layout selection (device counts x residency bills -> chosen layout),
2D-vs-solo bit-equality for the gated-MSI and shl2-MESI engines,
admission class keys splitting on the layout axis, and the per-device
residency arithmetic the across-device bin-packing proves against the
budget.  Runs on the conftest's forced 8-device CPU platform.
"""

import jax
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.sweep import SweepRunner
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

MSI = None   # default protocol from config_text(shared_mem=True)
SHL2_MESI = "pr_l1_sh_l2_mesi"


def _cfg(tiles=8, protocol=None, scheme="lax_barrier"):
    kw = {} if protocol is None else {"protocol": protocol}
    return SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme=scheme, **kw)))


def _traces(tiles, n, accesses=16):
    return [synthetic.memory_stress_trace(
        tiles, n_accesses=accesses, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=s)
        for s in range(1, n + 1)]


def _assert_equal(res_a, res_b, msg=""):
    np.testing.assert_array_equal(
        np.asarray(res_a.clock_ps), np.asarray(res_b.clock_ps),
        err_msg=f"clocks diverge {msg}")
    np.testing.assert_array_equal(
        np.asarray(res_a.instruction_count),
        np.asarray(res_b.instruction_count))
    if res_a.mem_counters is not None:
        for k, v in res_a.mem_counters.items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(res_b.mem_counters[k]),
                err_msg=f"mem counter {k} diverges {msg}")


# ---- bit-equality ----------------------------------------------------------


def test_2d_gated_msi_matches_solo():
    # Bl=1 cells keep the REAL per-phase lax.cond gating alive inside
    # each batch cell's tile exchange — the strongest engine shape
    sc = _cfg(8)
    traces = _traces(8, 2)
    r = SweepRunner(sc, traces, layout=(2, 2), phase_gate=True,
                    mem_gate_bytes=0)
    assert r.layout_name == "2d(b=2,t=2)"
    out = r.run(max_quanta=200_000)
    assert out.layout == "2d(b=2,t=2)"
    for b in range(2):
        solo = Simulator(sc, traces[b], mailbox_depth=r.mailbox_depth,
                         phase_gate=True, mem_gate_bytes=0).run()
        _assert_equal(out.results[b], solo, f"(2D gated sim {b})")
        # vacuity guard: real coherence traffic crossed the tile shards
        assert int(np.asarray(
            solo.mem_counters["l2_misses"]).sum()) > 0


def test_2d_shl2_mesi_matches_solo():
    sc = _cfg(8, protocol=SHL2_MESI)
    traces = _traces(8, 2)
    r = SweepRunner(sc, traces, layout=(2, 2), phase_gate=True,
                    mem_gate_bytes=0)
    out = r.run(max_quanta=200_000)
    for b in range(2):
        solo = Simulator(sc, traces[b], mailbox_depth=r.mailbox_depth,
                         phase_gate=True, mem_gate_bytes=0).run()
        _assert_equal(out.results[b], solo, f"(2D shl2 sim {b})")


def test_2d_vmapped_cells_match_solo():
    # Bl=2: batch cells vmap the px-sharded engine (batched collectives)
    sc = _cfg(8)
    traces = _traces(8, 4)
    r = SweepRunner(sc, traces, layout=(2, 2))
    assert r._sims_per_dev == 2
    out = r.run(max_quanta=200_000)
    for b in range(4):
        solo = Simulator(sc, traces[b], mailbox_depth=r.mailbox_depth,
                         phase_gate=False, mem_gate_bytes=0).run()
        _assert_equal(out.results[b], solo, f"(2D Bl=2 sim {b})")


# ---- layout selection ------------------------------------------------------


def test_layout_selection_matrix():
    # device counts x residency bills -> chosen layout, via the same
    # arithmetic SweepRunner's auto promotion runs
    sc = _cfg(8)
    traces = _traces(8, 2)
    probe = SweepRunner(sc, traces, layout="solo")
    per_sim = probe._per_sim_bill()
    blk2 = probe._per_sim_bill(tile_shards=2)
    blk4 = probe._per_sim_bill(tile_shards=4)
    assert per_sim > blk2 > blk4 > 0

    # fits one device -> no mesh promotion (None from the picker)
    assert probe._auto_mesh_layout(2, 8, 8, budget=None) is not None
    # budget below per-sim, above the 2-way block -> dt=2
    budget = (per_sim + blk2) // 2
    assert probe._auto_mesh_layout(2, 8, 8, budget=budget) == (2, 2)
    # budget below the 2-way block, above the 4-way -> dt=4
    budget = (blk2 + blk4) // 2
    assert probe._auto_mesh_layout(2, 8, 8, budget=budget) == (2, 4)
    # 2 devices can only split 2 ways; below that block nothing fits
    assert probe._auto_mesh_layout(2, 8, 2, budget=budget) is None
    # single device: no mesh to shard over
    assert probe._auto_mesh_layout(2, 8, 1, budget=budget) is None

    # end-to-end: the runner auto-promotes and proves per-device fit
    budget = (per_sim + blk2) // 2
    r = SweepRunner(sc, traces, hbm_budget_bytes=budget)
    assert r.layout_spec == (2, 2)
    assert r.device_breakdown()["total"] <= budget
    # explicit legacy kwargs still pin the old layouts
    assert SweepRunner(sc, traces, shard_batch=False).layout_spec \
        == "solo"
    assert SweepRunner(sc, _traces(8, 8),
                       shard_batch=True).layout_spec == "batch"


def test_layout_validation():
    sc = _cfg(8)
    traces = _traces(8, 2)
    with pytest.raises(ValueError, match="divide B"):
        SweepRunner(sc, traces, layout=(3, 2))
    with pytest.raises(ValueError, match="divide the tile count"):
        SweepRunner(sc, traces, layout=(2, 3))
    with pytest.raises(ValueError, match="not both"):
        SweepRunner(sc, traces, layout="solo", shard_batch=True)
    with pytest.raises(ValueError, match="unknown layout"):
        SweepRunner(sc, traces, layout="diagonal")


# ---- per-device residency arithmetic ---------------------------------------


def test_per_device_residency_arithmetic():
    from graphite_tpu.obs import ProfileSpec, TelemetrySpec
    from graphite_tpu.parallel.mesh import shard_split_bytes

    tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=16)
    prof = ProfileSpec(sample_interval_ps=1_000_000, n_samples=16)
    sc = _cfg(8)
    traces = _traces(8, 4)
    r = SweepRunner(sc, traces, layout=(2, 2), telemetry=tel,
                    profile=prof)
    state = r.sim.state.replace(telemetry=None, profile=None)
    split = shard_split_bytes(state)
    assert split["tile_local"] > 0 and split["replicated"] > 0

    bd = r.device_breakdown()          # 2 sims' tile blocks per device
    # state: full replicated control + half the big per-tile arrays
    assert bd["state"] == 2 * (split["replicated"]
                               + split["tile_local"] // 2)
    # telemetry ring replicates across tile shards; profile shards
    rtel = r.sim.telemetry_spec.ring_bytes()
    rprof = r.sim.profile_spec.ring_bytes(tile_shards=2)
    assert bd["telemetry"] == 2 * rtel
    assert bd["profile"] == 2 * rprof
    assert rprof < r.sim.profile_spec.ring_bytes()
    assert bd["total"] == sum(v for k, v in bd.items() if k != "total")
    # the whole-campaign bill strictly exceeds any device's share
    assert r.residency_breakdown()["total"] > bd["total"]


def test_profile_ring_shard_accounting():
    from graphite_tpu.obs import ProfileSpec

    class _P:
        n_tiles = 8
        mem = None

    spec = ProfileSpec(sample_interval_ps=1, n_samples=4).resolve(_P)
    S, T, m = spec.buffer_sig()[0]
    item = 8
    assert spec.ring_bytes() == (S * T * m + T * m + S + 2) * item
    assert spec.ring_bytes(tile_shards=2) == \
        (S * (T // 2) * m + (T // 2) * m + S + 2) * item
    with pytest.raises(ValueError, match="divisible"):
        spec.ring_bytes(tile_shards=3)


# ---- admission -------------------------------------------------------------


def _measure(job, budget=0, n_devices=1, batch_size=4):
    from graphite_tpu.serve.admission import AdmissionController

    return AdmissionController(hbm_budget_bytes=budget,
                               batch_size=batch_size,
                               n_devices=n_devices)


def test_admission_class_key_splits_on_layout():
    from graphite_tpu.serve.admission import measure_job
    from graphite_tpu.serve.job import Job

    sc = _cfg(8, scheme="lax")
    trace = _traces(8, 1, accesses=12)[0]
    job = Job("k0", sc, trace, seed=1)
    m = measure_job(job, mailbox_depth=8, pad_length=64)
    budget = (m.per_sim_total + m.device_block(2)["total"]) // 2

    solo_key = _measure(job).class_key(job)           # budget off
    mesh_key = _measure(job, budget=budget,
                        n_devices=8).class_key(job)
    # identical program class, different LAYOUT axis — never co-batch
    assert solo_key[:-1] == mesh_key[:-1]
    assert solo_key[-1] == ("solo",)
    assert mesh_key[-1][0] == "2d" and mesh_key[-1][2] > 1
    assert solo_key != mesh_key


def test_admission_bin_packs_across_devices():
    from graphite_tpu.analysis.cost import ResidencyBudgetError
    from graphite_tpu.serve.admission import measure_job
    from graphite_tpu.serve.job import Job

    from graphite_tpu.engine.simulator import auto_mailbox_depth
    from graphite_tpu.serve.admission import _pow2_bucket

    sc = _cfg(8, scheme="lax")
    trace = _traces(8, 1, accesses=12)[0]
    job = Job("b0", sc, trace, seed=1)
    # measure at the controller's OWN bucketed depth/length, so the
    # rejection breakdown is comparable number for number
    m = measure_job(
        job,
        mailbox_depth=_pow2_bucket(auto_mailbox_depth(job.trace), 2),
        pad_length=_pow2_bucket(job.trace.length, 16))
    budget = (m.per_sim_total + m.device_block(2)["total"]) // 2

    # one device: the round-13 never-fits rejection, breakdown attached
    with pytest.raises(ResidencyBudgetError,
                       match="can never fit") as ei:
        _measure(job, budget=budget).admit(job)
    assert ei.value.breakdown["total"] == m.per_sim_total

    # eight devices: admitted under the 2D layout, per-device block
    # PROVEN <= the budget, capacity accounting devices x budget
    ctrl = _measure(job, budget=budget, n_devices=8)
    cls, _ = ctrl.admit(job)
    assert cls.tile_shards == 2 and cls.batch_shards >= 1
    assert cls.batch_cap >= 1
    assert cls.batch_cap % cls.batch_shards == 0
    dev = cls.device_breakdown()
    assert dev["total"] <= budget
    # the whole batch exceeds one budget — that is the point
    if cls.batch_cap > 1:
        assert cls.breakdown(cls.batch_cap)["total"] > budget

    # a budget below even the maximal split still rejects, naming the
    # per-device attempt
    with pytest.raises(ResidencyBudgetError, match="per-device block"):
        _measure(job, budget=m.device_block(8)["total"] // 2,
                 n_devices=8).admit(job)
    # dt need not divide n_devices: with 6 devices and an 8-tile job,
    # the 4-way split (one batch shard, two devices idle) is still
    # found when only it fits
    blk4 = m.device_block(4)["total"]
    ctrl6 = _measure(job, budget=blk4 + 1, n_devices=6)
    cls6, _ = ctrl6.admit(Job("b6", sc, trace, seed=1))
    assert cls6.tile_shards == 4 and cls6.batch_shards == 1


def test_admission_capacity_accounts_devices():
    from graphite_tpu.serve.admission import measure_job, plan_layout
    from graphite_tpu.serve.job import Job

    sc = _cfg(8, scheme="lax")
    trace = _traces(8, 1, accesses=12)[0]
    job = Job("c0", sc, trace, seed=1)
    m = measure_job(job, mailbox_depth=8, pad_length=64)
    blk2 = m.device_block(2)["total"]
    # budget fits exactly one sim's 2-way block per device: with 8
    # devices (4 batch shards x 2 tile shards) capacity is 4, not 1
    plan = plan_layout(m, hbm_budget_bytes=blk2 + 1, batch_size=16,
                       n_devices=8)
    assert plan["tag"] == ("2d", 4, 2)
    assert plan["batch_cap"] == 4
    # batch_size still clamps
    plan = plan_layout(m, hbm_budget_bytes=blk2 + 1, batch_size=2,
                       n_devices=8)
    assert plan["batch_cap"] == 2 and plan["batch_shards"] == 2
