"""Directory-entry scheme variants (`directory_schemes/directory_entry_*.cc`,
`directory_type.h:3`): full_map, limited_no_broadcast, limited_broadcast,
ackwise, limitless.

The reference's schemes differ in how the hardware tracks sharers beyond
`[dram_directory] max_hw_sharers` (k); the vectorized engine keeps the exact
sharer bitvector as functional ground truth and varies the message traffic /
timing, which is everything the timing model observes:

 - limited_no_broadcast: a (k+1)-th read-sharer displaces one tracked
   sharer (extra INV traffic, visible in the invalidations counter);
 - ackwise / limited_broadcast: EX on an overflowed entry broadcasts the
   INV sweep to all tiles (dir_broadcasts counter);
 - limitless: accesses to overflowed entries pay the software trap penalty
   (`[limitless] software_trap_penalty`) — visible as added latency.
"""

import numpy as np

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.trace.schema import TraceBatch, TraceBuilder


def make_config(n_tiles, dir_type, k=2, trap=200):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = magic
[dram_directory]
directory_type = {dir_type}
max_hw_sharers = {k}
[limitless]
software_trap_penalty = {trap}
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def run_sharers_then_write(n_tiles, dir_type, k=2, trap=200, protocol=None):
    """All tiles read one line (n sharers), then tile 0 writes it (EX)."""
    sc = make_config(n_tiles, dir_type, k=k, trap=trap)
    if protocol:
        sc.cfg.set("caching_protocol/type", protocol)
    addr = 0x100
    builders = []
    for t in range(n_tiles):
        b = TraceBuilder()
        if t == 0:
            b.barrier_init(0, n_tiles)
        b.load_check(addr, 0)
        b.barrier_wait(0)
        if t == 0:
            b.store_value(addr, 9)
        b.barrier_wait(0)
        if t != 0:
            b.load_check(addr, 9)
        builders.append(b)
    return Simulator(sc, TraceBatch.from_builders(builders)).run()


class TestLimitedNoBroadcast:
    def test_displacement_invalidation(self):
        """With k=2 and 4 readers, sharers 3 and 4 each displace a tracked
        sharer: extra INVs served during the *read* phase (the reference's
        addSharer-failure → getSharerToInvalidate path)."""
        full = run_sharers_then_write(4, "full_map")
        lim = run_sharers_then_write(4, "limited_no_broadcast", k=2)
        assert full.func_errors == 0 and lim.func_errors == 0
        # full_map: one sweep invalidates 4 sharers minus the upgrading
        # writer's own (handled by the upgrade eviction) = 3 served INVs.
        # limited_nb: 2 displacement INVs during reads; the EX sweep then
        # only finds <= 2 tracked sharers.
        assert lim.mem_counters["invalidations"].sum() >= 2
        # the write-phase sweep is smaller than full_map's
        assert lim.mem_counters["dir_broadcasts"].sum() == 0

    def test_functional_correctness_many_tiles(self):
        res = run_sharers_then_write(8, "limited_no_broadcast", k=1)
        assert res.func_errors == 0

    def test_modified_to_shared_at_capacity(self):
        """k=1: writer holds M; a reader's SH cannot add a second tracked
        sharer — the owner is FLUSHed out (addSharer failure on M→S) and
        values still propagate."""
        sc = make_config(2, "limited_no_broadcast", k=1)
        addr = 0x200
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 77)      # M at tile 0
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.load_check(addr, 77)       # refetch after being flushed out
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 77)       # SH displaces the M owner
        b1.barrier_wait(0)
        res = Simulator(sc, TraceBatch.from_builders([b0, b1])).run()
        assert res.func_errors == 0
        mc = res.mem_counters
        # tile 0 lost its copy to the FLUSH: its later read misses L1D
        assert mc["l1d_read_misses"][0] >= 1

    def test_mosi_displacement(self):
        res = run_sharers_then_write(
            6, "limited_no_broadcast", k=2,
            protocol="pr_l1_pr_l2_dram_directory_mosi")
        assert res.func_errors == 0


class TestAckwise:
    def test_broadcast_on_overflow(self):
        res = run_sharers_then_write(4, "ackwise", k=2)
        assert res.func_errors == 0
        assert res.mem_counters["dir_broadcasts"].sum() >= 1

    def test_no_broadcast_below_capacity(self):
        res = run_sharers_then_write(4, "ackwise", k=8)
        assert res.func_errors == 0
        assert res.mem_counters["dir_broadcasts"].sum() == 0

    def test_limited_broadcast_same_model(self):
        res = run_sharers_then_write(4, "limited_broadcast", k=2)
        assert res.func_errors == 0
        assert res.mem_counters["dir_broadcasts"].sum() >= 1

    def test_timing_matches_full_map_zero_contention(self):
        """On the magic net the broadcast costs nothing extra (no per-hop
        contention): completion equals full_map — documents that the scheme
        changes traffic, not the ack-wait set."""
        full = run_sharers_then_write(4, "full_map")
        ack = run_sharers_then_write(4, "ackwise", k=2)
        assert ack.completion_time_ps == full.completion_time_ps


class TestLimitless:
    def test_software_trap_latency(self):
        full = run_sharers_then_write(4, "full_map")
        lim = run_sharers_then_write(4, "limitless", k=2, trap=200)
        assert lim.func_errors == 0
        # the 3rd/4th sharer adds + the EX sweep on the overflowed entry
        # each pay the 200-cycle trap at the DIRECTORY frequency
        assert lim.completion_time_ps > full.completion_time_ps
        delta_ns = (lim.completion_time_ps - full.completion_time_ps) / 1000
        assert delta_ns >= 200  # at least one trap (1 cycle = 1 ns @ 1 GHz)

    def test_no_trap_below_capacity(self):
        full = run_sharers_then_write(4, "full_map")
        lim = run_sharers_then_write(4, "limitless", k=64, trap=200)
        assert lim.completion_time_ps == full.completion_time_ps


class TestFullMapUnchanged:
    def test_mosi_all_schemes_functional(self):
        for scheme in ("full_map", "ackwise", "limitless"):
            res = run_sharers_then_write(
                4, scheme, k=2,
                protocol="pr_l1_pr_l2_dram_directory_mosi")
            assert res.func_errors == 0, scheme


class TestSharedL2Schemes:
    """The embedded shared-L2 directory (`l2_directory_cfg.cc` analog)
    supports the same schemes over its L1-sharer lists."""

    def test_shl2_ackwise_broadcast(self):
        res = run_sharers_then_write(4, "ackwise", k=2,
                                     protocol="pr_l1_sh_l2_msi")
        assert res.func_errors == 0
        assert res.mem_counters["dir_broadcasts"].sum() >= 1

    def test_shl2_limited_no_broadcast(self):
        lim = run_sharers_then_write(4, "limited_no_broadcast", k=2,
                                     protocol="pr_l1_sh_l2_msi")
        assert lim.func_errors == 0
        assert lim.mem_counters["invalidations"].sum() >= 2
        assert lim.mem_counters["dir_broadcasts"].sum() == 0

    def test_shl2_limitless_trap(self):
        full = run_sharers_then_write(4, "full_map",
                                      protocol="pr_l1_sh_l2_mesi")
        lim = run_sharers_then_write(4, "limitless", k=2, trap=200,
                                     protocol="pr_l1_sh_l2_mesi")
        assert lim.func_errors == 0
        assert lim.completion_time_ps > full.completion_time_ps

    def test_shl2_mesi_capacity_downgrade(self):
        """k=1 on MESI: the E owner is flushed out when a second reader
        arrives; EXCLUSIVE is re-granted to the newcomer."""
        res = run_sharers_then_write(2, "limited_no_broadcast", k=1,
                                     protocol="pr_l1_sh_l2_mesi")
        assert res.func_errors == 0
