"""Heterogeneous per-tile core models (`[tile] model_list`,
`config.cc:365-472`): a mesh mixing simple and iocoom tiles must time each
tile exactly like its homogeneous counterpart."""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.trace.schema import NO_REG, Op, TraceBatch, TraceBuilder


def make_config(model_list=None, n_tiles=2):
    tile_section = (
        f"[tile]\nmodel_list = {model_list}\n" if model_list else "")
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = false
{tile_section}
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
imul = 3
[core/iocoom]
num_store_buffer_entries = 20
num_outstanding_loads = 32
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def dep_chain_builder(n=12):
    """Serially dependent imuls: iocoom stalls on the scoreboard, simple
    charges the static cost — the models must disagree."""
    b = TraceBuilder()
    for i in range(n):
        b.instr(Op.IMUL, rregs=(1,), wreg=1)
    return b


def run(sc, builders):
    return Simulator(sc, TraceBatch.from_builders(builders)).run()


class TestHeterogeneousCores:
    def test_mixed_matches_homogeneous(self):
        mixed = make_config("<1, simple> <1, iocoom>")
        all_simple = make_config("<2, simple>")
        all_iocoom = make_config("<2, iocoom>")

        r_mixed = run(mixed, [dep_chain_builder(), dep_chain_builder()])
        r_simple = run(all_simple, [dep_chain_builder(), dep_chain_builder()])
        r_iocoom = run(all_iocoom, [dep_chain_builder(), dep_chain_builder()])

        assert r_mixed.clock_ps[0] == r_simple.clock_ps[0]
        assert r_mixed.clock_ps[1] == r_iocoom.clock_ps[1]
        # the two models genuinely differ on a dependency chain
        assert r_simple.clock_ps[0] != r_iocoom.clock_ps[0]

    def test_model_list_parsing_mismatch_raises(self):
        with pytest.raises(ValueError):
            make_config("<1, simple>")  # only 1 of 2 tiles initialized

    def test_unknown_core_type_raises(self):
        sc = make_config("<2, bogus>")
        with pytest.raises(NotImplementedError):
            Simulator(sc, TraceBatch.from_builders(
                [TraceBuilder().instr(Op.IALU), TraceBuilder()]))
