"""Batched simulation campaigns (graphite_tpu/sweep/): trace packing,
per-sim bit-equality of the vmapped program against sequential runs, and
recompile-free knob tracing.

The two contract pins:
 - a B=8 same-geometry sweep is BIT-IDENTICAL per-sim to 8 sequential
   Simulator runs (clocks + memory counters + quanta) — vmap's
   while_loop batching rule select-freezes finished sims, so batching
   changes wall-clock shape only, never results;
 - one jax.jit lowering serves a >= 4-point timing-knob grid with zero
   recompiles (compile-count probe), and each traced-knob point matches
   a run with the same values baked statically into the params.
"""

import dataclasses

import jax
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.sweep import (
    Knobs, SweepRunner, grid_points, pack_traces,
)
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import NO_REG, Op


TILES = 8


def _config(clock="lax"):
    return SimConfig(ConfigFile.from_string(config_text(
        TILES, shared_mem=True, clock_scheme=clock)))


def _trace(seed, n=16):
    return synthetic.memory_stress_trace(
        TILES, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _assert_results_equal(ra, rb, msg=""):
    np.testing.assert_array_equal(ra.clock_ps, rb.clock_ps, err_msg=msg)
    np.testing.assert_array_equal(
        ra.instruction_count, rb.instruction_count, err_msg=msg)
    assert ra.n_quanta == rb.n_quanta, msg
    assert (ra.mem_counters is None) == (rb.mem_counters is None), msg
    if ra.mem_counters is not None:
        for k in ra.mem_counters:
            np.testing.assert_array_equal(
                ra.mem_counters[k], rb.mem_counters[k],
                err_msg=f"{msg}: {k}")


class TestPack:
    def test_pads_to_common_layout_and_roundtrips(self):
        traces = [_trace(s, n) for s, n in ((1, 8), (2, 16), (3, 12))]
        pack = pack_traces(traces, seeds=[1, 2, 3])
        assert pack.n_sims == 3 and pack.n_tiles == TILES
        assert pack.length == max(t.length for t in traces)
        assert pack.lengths.tolist() == [t.length for t in traces]
        assert pack.seeds.tolist() == [1, 2, 3]
        for b, t in enumerate(traces):
            back = pack.sim(b)
            # original records bit-exact; the tail is inert NOP padding
            for f in pack._TRACE_FIELDS:
                np.testing.assert_array_equal(
                    getattr(back, f)[:, : t.length], getattr(t, f),
                    err_msg=f"sim {b} field {f}")
            assert (back.op[:, t.length:] == int(Op.NOP)).all()
            assert (back.rreg0[:, t.length:] == NO_REG).all()
            assert (back.dyn_ps[:, t.length:] == 0).all()

    def test_rejects_mixed_geometry(self):
        other = synthetic.memory_stress_trace(
            TILES * 2, n_accesses=8, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.5, seed=1)
        with pytest.raises(ValueError, match="tile count"):
            pack_traces([_trace(1), other])

    def test_replicate(self):
        pack = pack_traces([_trace(5)]).replicate(3)
        assert pack.n_sims == 3
        np.testing.assert_array_equal(pack.op[0], pack.op[2])


class TestKnobs:
    def test_grid_points_cross_product(self):
        pts = grid_points(dram_latency_ns=[50, 100],
                          hop_latency_cycles=[1, 2, 3])
        assert len(pts) == 6
        assert pts[0] == {"dram_latency_ns": 50, "hop_latency_cycles": 1}
        assert pts[-1] == {"dram_latency_ns": 100, "hop_latency_cycles": 3}

    def test_unknown_knob_rejected(self):
        with pytest.raises(ValueError, match="unknown knob"):
            grid_points(dram_latency=[1])
        base = Knobs.from_params(Simulator(_config(), _trace(1)).params, 0)
        with pytest.raises(ValueError, match="unknown knob"):
            Knobs.stack(base, [{"nope": 3}])

    def test_from_params_reads_static_values(self):
        sim = Simulator(_config("lax_barrier"), _trace(1))
        kn = Knobs.from_params(sim.params, sim.quantum_ps)
        mp = sim.params.mem
        assert int(kn.dram_latency_ns) == mp.dram_latency_ns
        assert int(kn.dir_access_cycles) == mp.dir_access_cycles
        assert int(kn.hop_latency_cycles) == mp.hop_latency_cycles
        assert int(kn.sync_delay_cycles) == mp.sync_delay_cycles
        assert int(kn.quantum_ps) == sim.quantum_ps


@pytest.fixture(scope="module")
def b8_sequential_refs():
    """8 sequential Simulator runs of the B=8 campaign traces (shared by
    both batching-program variants below)."""
    from graphite_tpu.engine.simulator import auto_mailbox_depth

    sc = _config("lax")
    traces = [_trace(seed) for seed in range(1, 9)]
    depth = max(auto_mailbox_depth(t) for t in traces)
    refs = [Simulator(sc, t, mailbox_depth=depth).run() for t in traces]
    return sc, traces, depth, refs


class TestSweepEqualsSequential:
    # the forced-vmap B=8 variant is `slow` (one extra B=8-wide compile):
    # the vmap select-freeze mechanism is already tier-1-pinned at B=2 by
    # test_vmapped_knob_grid_matches_sequential_static and at B=4 by the
    # regress --smoke rung; tier-1 pins B=8 through the runner's actual
    # program choice
    @pytest.mark.parametrize(
        "shard",
        [None, pytest.param(False, marks=pytest.mark.slow)],
        ids=["auto_shard", "vmap"])
    def test_b8_bit_identical_to_sequential_runs(
            self, b8_sequential_refs, shard):
        """The acceptance pin: a B=8 same-geometry sweep == 8 sequential
        Simulator runs, bit-exact (clocks + memory counters + quanta) —
        for BOTH batching programs: batch-axis shard_map (auto under the
        suite's 8-virtual-device platform) and plain vmap (the
        while_loop batching rule's select-freeze)."""
        sc, traces, depth, refs = b8_sequential_refs
        sweep = SweepRunner(sc, traces, mailbox_depth=depth,
                            shard_batch=shard)
        if shard is None:
            assert sweep.shard_batch  # conftest provides 8 devices
        out = sweep.run()
        assert len(out.results) == 8
        for b in range(8):
            _assert_results_equal(out.results[b], refs[b], msg=f"sim {b}")
        # per-sim gate observability demuxes too
        assert out.phase_skips is not None and len(out.phase_skips) == 8

    def test_validations(self):
        sc = _config()
        with pytest.raises(ValueError, match="counts must match"):
            SweepRunner(sc, [_trace(1), _trace(2)], [{}] * 3)
        with pytest.raises(ValueError, match="single-device"):
            SweepRunner(sc, [_trace(1)], stream=True)
        # mixed memory/memoryless campaign cannot share one program
        b = _trace(2)
        memoryless = dataclasses.replace(
            b, flags=np.zeros_like(b.flags),
            op=np.where(b.op < 20, np.uint8(Op.IALU), b.op))
        with pytest.raises(ValueError, match="agree on touching memory"):
            SweepRunner(sc, [_trace(1), memoryless])


class TestKnobTracing:
    def test_grid_single_compile_matches_static_params(self):
        """One jit lowering serves a 4-point knob grid (zero recompiles,
        compile-count probe) and every traced point reproduces a
        fresh static-params run bit-exactly — including a traced
        lax_barrier quantum."""
        from graphite_tpu.engine.state import DeviceTrace
        from graphite_tpu.engine.step import run_simulation

        sc = _config("lax_barrier")
        batch = _trace(3)
        sim = Simulator(sc, batch)
        params, qps = sim.params, sim.quantum_ps
        state0 = sim.state
        trace = DeviceTrace.from_batch(batch)

        runner = jax.jit(lambda st, kn: run_simulation(
            params, trace, st, kn.quantum_ps, 100_000, knobs=kn))
        base = Knobs.from_params(params, qps)
        points = grid_points(dram_latency_ns=[40, 220],
                             hop_latency_cycles=[1, 4])
        points[1]["quantum_ps"] = 7_000_000   # quantum is traced too
        points[2]["sync_delay_cycles"] = 5
        points[3]["dir_access_cycles"] = 11
        assert len(points) >= 4
        got = []
        for p in points:
            kn = jax.tree_util.tree_map(
                lambda x: x[0], Knobs.stack(base, [p]))
            st, nq, deadlock, _ = runner(state0, kn)
            assert not bool(deadlock)
            got.append((np.asarray(st.core.clock_ps), int(nq),
                        np.asarray(st.mem.counters.dram_total_lat_ps)))
        # the probe: 4 distinct knob points, ONE compiled executable
        assert runner._cache_size() == 1
        # knobs change results (they are live, not dead operands)
        assert not (got[0][0] == got[3][0]).all()

        # static-baked reference runs are a compile each: verify two
        # points — one carrying the traced quantum, one the remaining
        # knobs (the others exercise the same replace path)
        for p, (clk, nq, dram_lat) in (
                (points[1], got[1]), (points[3], got[3])):
            mp2 = dataclasses.replace(
                params.mem,
                **{k: v for k, v in p.items() if k != "quantum_ps"})
            params2 = dataclasses.replace(params, mem=mp2)
            q2 = p.get("quantum_ps", qps)
            st2, nq2, _, _ = jax.jit(
                lambda st: run_simulation(params2, trace, st, q2,
                                          100_000))(state0)
            np.testing.assert_array_equal(
                np.asarray(st2.core.clock_ps), clk, err_msg=str(p))
            np.testing.assert_array_equal(
                np.asarray(st2.mem.counters.dram_total_lat_ps), dram_lat,
                err_msg=str(p))
            assert int(nq2) == nq, p

    def test_vmapped_knob_grid_matches_sequential_static(self):
        """End-to-end: a knob grid through SweepRunner (one trace
        replicated) matches per-point Simulators built from configs
        with the values baked in."""
        sc = _config("lax")
        batch = _trace(4)
        points = [{"dram_latency_ns": 55}, {"dram_latency_ns": 210}]
        sweep = SweepRunner(sc, [batch], points)
        out = sweep.run()
        assert out.knobs.point(0)["dram_latency_ns"] == 55
        for b, p in enumerate(points):
            sim = Simulator(sc, batch, mailbox_depth=sweep.mailbox_depth)
            sim.params = dataclasses.replace(
                sim.params,
                mem=dataclasses.replace(sim.params.mem, **p))
            _assert_results_equal(out.results[b], sim.run(), msg=str(p))
        # the two points must actually differ
        assert (out.results[0].completion_time_ps
                != out.results[1].completion_time_ps)
